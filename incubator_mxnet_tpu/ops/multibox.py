"""MultiBox SSD ops (ref src/operator/contrib/multibox_prior.cc,
multibox_target.cc, multibox_detection.cc — required by BASELINE config 4).

TPU-native: everything is dense, statically-shaped XLA — IoU matrices as
batched einsums, NMS as a fixed-trip-count lax.fori_loop with masking (no
dynamic shapes, so the whole detection head compiles onto the chip).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ndarray import NDArray, _apply, _to_nd

__all__ = ["MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection", "box_iou"]


def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0),
                  offsets=(0.5, 0.5)):
    """Generate anchor boxes per feature-map pixel (ref multibox_prior.cc).

    data: (N, C, H, W). Returns (1, H*W*(len(sizes)+len(ratios)-1), 4) corners
    normalised to [0,1] — matches MXNet's anchor layout.
    """
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w

    def fn(_):
        cy = (jnp.arange(h) + offsets[0]) * step_y
        cx = (jnp.arange(w) + offsets[1]) * step_x
        cy, cx = jnp.meshgrid(cy, cx, indexing="ij")         # (H, W)
        boxes = []
        # MXNet order: (s_i, r_0) for all sizes, then (s_0, r_j) for ratios[1:]
        for s in sizes:
            r = ratios[0]
            bw, bh = s * jnp.sqrt(r) / 2, s / jnp.sqrt(r) / 2
            boxes.append((bw, bh))
        for r in ratios[1:]:
            s = sizes[0]
            bw, bh = s * jnp.sqrt(r) / 2, s / jnp.sqrt(r) / 2
            boxes.append((bw, bh))
        anchors = []
        for bw, bh in boxes:
            a = jnp.stack([cx - bw, cy - bh, cx + bw, cy + bh], axis=-1)  # (H,W,4)
            anchors.append(a)
        out = jnp.stack(anchors, axis=2).reshape(-1, 4)      # (H*W*A, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out[None]
    return _apply(fn, _to_nd(data))


def box_iou(a, b):
    """IoU matrix between (Na,4) and (Nb,4) corner boxes."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1, negative_mining_ratio=-1,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    """Assign training targets (ref multibox_target.cc).

    anchor: (1, A, 4); label: (N, M, 5) [cls, xmin, ymin, xmax, ymax] with
    cls == -1 padding; cls_pred: (N, num_cls+1, A) (used for hard mining).
    Returns [loc_target (N, A*4), loc_mask (N, A*4), cls_target (N, A)].
    """
    v = jnp.asarray(variances)

    def one_sample(lbl, cp):
        valid = lbl[:, 0] >= 0                                  # (M,)
        gt = lbl[:, 1:5]
        anc = anchor._data[0] if isinstance(anchor, NDArray) else anchor[0]
        iou = box_iou(anc, gt)                                  # (A, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)                       # (A,)
        best_iou = jnp.max(iou, axis=1)
        # each gt's best anchor is forced positive
        best_anchor_for_gt = jnp.argmax(iou, axis=0)            # (M,)
        forced = jnp.zeros(anc.shape[0], bool).at[best_anchor_for_gt].set(valid)
        pos = (best_iou >= overlap_threshold) | forced
        matched_gt = gt[best_gt]                                # (A, 4)
        matched_cls = lbl[best_gt, 0]
        # encode loc targets (center/size, variance-normalised)
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        gw = jnp.maximum(matched_gt[:, 2] - matched_gt[:, 0], 1e-8)
        gh = jnp.maximum(matched_gt[:, 3] - matched_gt[:, 1], 1e-8)
        gcx = (matched_gt[:, 0] + matched_gt[:, 2]) / 2
        gcy = (matched_gt[:, 1] + matched_gt[:, 3]) / 2
        loc = jnp.stack([(gcx - acx) / jnp.maximum(aw, 1e-8) / v[0],
                         (gcy - acy) / jnp.maximum(ah, 1e-8) / v[1],
                         jnp.log(gw / jnp.maximum(aw, 1e-8)) / v[2],
                         jnp.log(gh / jnp.maximum(ah, 1e-8)) / v[3]], axis=-1)
        loc = jnp.where(pos[:, None], loc, 0.0)
        mask = jnp.where(pos[:, None], jnp.ones_like(loc), 0.0)
        cls_t = jnp.where(pos, matched_cls + 1.0, 0.0)          # 0 = background
        if negative_mining_ratio > 0:
            # hard negative mining by background confidence
            bg_prob = jax.nn.softmax(cp, axis=0)[0]             # (A,)
            neg_score = jnp.where(pos, jnp.inf, bg_prob)
            n_pos = jnp.sum(pos)
            n_neg = jnp.minimum(
                (negative_mining_ratio * n_pos).astype(jnp.int32),
                anc.shape[0] - n_pos.astype(jnp.int32))
            order = jnp.argsort(neg_score)                      # hardest first
            rank = jnp.zeros(anc.shape[0], jnp.int32).at[order].set(
                jnp.arange(anc.shape[0], dtype=jnp.int32))
            keep_neg = rank < n_neg
            cls_t = jnp.where(pos, cls_t,
                              jnp.where(keep_neg, 0.0, float(ignore_label)))
        return loc.reshape(-1), mask.reshape(-1), cls_t

    def fn(anc, lbl, cp):
        loc, mask, cls_t = jax.vmap(one_sample)(lbl, cp)
        return loc, mask, cls_t

    return _apply(fn, _to_nd(anchor), _to_nd(label), _to_nd(cls_pred))


def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5, force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode + NMS (ref multibox_detection.cc).

    cls_prob: (N, num_cls+1, A); loc_pred: (N, A*4); anchor: (1, A, 4).
    Returns (N, A, 6): [cls_id, score, xmin, ymin, xmax, ymax], cls_id=-1 ⇒
    suppressed. Fixed shapes: NMS is a masked fori_loop.
    """
    v = jnp.asarray(variances)

    def one(cp, lp, anc):
        A = anc.shape[0]
        scores = cp[1:]                                         # (C, A) drop bg
        cls_id = jnp.argmax(scores, axis=0)                     # (A,)
        score = jnp.max(scores, axis=0)
        # decode
        loc = lp.reshape(A, 4)
        aw = anc[:, 2] - anc[:, 0]
        ah = anc[:, 3] - anc[:, 1]
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        cx = loc[:, 0] * v[0] * aw + acx
        cy = loc[:, 1] * v[1] * ah + acy
        w = jnp.exp(loc[:, 2] * v[2]) * aw / 2
        h = jnp.exp(loc[:, 3] * v[3]) * ah / 2
        boxes = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        keep = score > threshold
        order = jnp.argsort(-jnp.where(keep, score, -jnp.inf))
        boxes_o = boxes[order]
        score_o = jnp.where(keep[order], score[order], 0.0)
        cls_o = jnp.where(keep[order], cls_id[order].astype(jnp.float32), -1.0)
        iou = box_iou(boxes_o, boxes_o)

        def body(i, alive):
            sup = (iou[i] > nms_threshold) & (jnp.arange(A) > i) & alive[i]
            if not force_suppress:
                sup = sup & (cls_o == cls_o[i])
            return alive & ~sup

        alive = lax.fori_loop(0, A, body, cls_o >= 0)
        cls_final = jnp.where(alive, cls_o, -1.0)
        return jnp.concatenate([cls_final[:, None], score_o[:, None], boxes_o],
                               axis=-1)

    def fn(cp, lp, anc):
        return jax.vmap(lambda c, l: one(c, l, anc[0]))(cp, lp)

    return _apply(fn, _to_nd(cls_prob), _to_nd(loc_pred), _to_nd(anchor))
