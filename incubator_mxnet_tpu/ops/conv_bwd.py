"""Fused 3x3/stride-1 conv BACKWARD Pallas kernel (dgrad + wgrad in one pass).

The pilot kernel behind docs/PERF_RESNET.md's central claim: XLA's
conv-backward codegen emits ~2.7x the fused-ideal HBM traffic (42.2 GB of
the ResNet-50 step's 76.4 GB), because dgrad and wgrad are separate ops —
each re-reads dy, dgrad materializes a padded/dilated grad, and wgrad runs
fp32 accumulation sweeps.  This kernel computes BOTH gradients in a single
grid pass that reads x once, reads dy once, and writes dx once:

    bytes = |x| + |dy| + |dx| + |dw|        (the fused ideal)

Formulation (NHWC, HWIO, stride 1, SAME padding, correlation semantics —
matches ``lax.conv_general_dilated``; ref src/operator/nn/convolution-inl.h
backward, re-derived for the MXU instead of im2col+GEMM):

    y[n,p,q,k]  = sum_{r,s,c} x[n, p+r-1, q+s-1, c] * w[r,s,c,k]
    dx[n,a,b,c] = sum_{r,s}   dy[n, a+1-r, b+1-s, :] @ w[r,s].T   (9 taps)
    dw[r,s,c,k] = sum_{n,p,q} x[n, p+r-1, q+s-1, c] * dy[n,p,q,k]

Each tap is a dense [M, K] x [K, C] (dgrad) or [M, C].T x [M, K] (wgrad)
matmul over the valid spatial overlap — 18 MXU matmuls per grid step, all
operands resident in VMEM.  The grid walks batch chunks sequentially; dw
accumulates in an fp32 VMEM scratch across steps (the flash-attention carry
idiom) and is written on the last step.  fp32 accumulation for BOTH outputs
(dx is cast to the activation dtype only on the final store), matching
XLA's conv-backward numerics.

Used by ``conv3x3_s1`` (custom_vjp) — forward stays XLA's conv (already at
the bandwidth roofline); backward takes this kernel when the shape is legal.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["conv3x3_bwd", "conv3x3_bwd_legal", "conv3x3_s1", "conv3x3_bytes"]


def _interpret():
    from ..config import get_env
    return get_env("MXTPU_FLASH_INTERPRET")


def _on_tpu():
    return jax.devices()[0].platform in ("tpu", "axon")


# VMEM budget for one grid step's resident blocks (x, dy bf16 in; dx out;
# padded scratch; fp32 dx accumulator). The compiler double-buffers the
# in/out blocks on top of this (~1.5x observed), so 6 MB keeps the total
# under the 16 MB scoped-vmem limit.
_VMEM_BUDGET = 6 * 1024 * 1024


def _per_img_bytes(H, W, C, K, itemsize):
    """Resident VMEM bytes per image: x/dx blocks (C lanes), dy block
    (K lanes), the padded copies, the im2col patch buffer (9*max(C,K)
    lanes — the big one), and the fp32 dx matmul result on the stack.

    Shared between the block chooser and the legality gate so the two
    can never disagree about what fits."""
    pad = (H + 2) * (W + 2)
    return (H * W * (2 * itemsize * C + itemsize * K + 4 * C)
            + pad * itemsize * (C + K)
            + H * W * 9 * max(C, K) * itemsize)


def _auto_block_n(N, H, W, C, K, itemsize):
    """Largest batch-chunk dividing N whose resident blocks fit the budget."""
    per_img = _per_img_bytes(H, W, C, K, itemsize)
    bn = max(1, _VMEM_BUDGET // max(per_img, 1))
    while bn > 1 and N % bn:
        bn -= 1
    return min(bn, N)


def conv3x3_bwd_legal(x_shape, w_shape, stride=(1, 1), padding=(1, 1),
                      dilation=(1, 1), groups=1, itemsize=4):
    """Capability: 3x3, stride 1, SAME (pad 1), dense, NHWC/HWIO, C and K
    lane-packable (mult of 8); TPU or interpret mode."""
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    KH, KW, C, K = w_shape
    if (KH, KW) != (3, 3) or x_shape[3] != C:
        return False
    if tuple(stride) != (1, 1) or tuple(padding) != (1, 1):
        return False
    if tuple(dilation) != (1, 1) or groups != 1:
        return False
    if C % 8 or K % 8:
        return False
    # the (9C, K) fp32 dw accumulator must fit VMEM alongside the patch
    # buffer — C=K=512 (conv5-class) exceeds it in this single-pass design
    if 9 * C * K * 4 > _VMEM_BUDGET:
        return False
    # even at block_n=1 the per-image resident footprint (dominated by the
    # H*W*9*max(C,K) patch buffer) must fit, or the kernel fails scoped-VMEM
    # allocation at compile time instead of falling back to XLA
    _, H, W, _ = x_shape
    if _per_img_bytes(H, W, C, K, itemsize) > _VMEM_BUDGET:
        return False
    from ..config import get_env
    if not get_env("MXTPU_CONV_BWD_PALLAS"):
        return False
    try:
        import jax.experimental.pallas  # noqa: F401
    except ImportError:
        return False
    return _on_tpu() or _interpret()


def _conv_bwd_kernel(x_ref, dy_ref, wd_ref, dx_ref, dw_ref, xp, dyp, pb, dwa,
                     *, H, W):
    """One batch-chunk step, im2col-in-VMEM form: ONE MXU matmul per
    gradient direction instead of 9 small taps each.

    x and dy are copied into zero-padded VMEM scratch (halo 1); the 9
    shifted views are laid side-by-side in a patch buffer ``pb``
    (im2col, entirely in VMEM — HBM traffic stays at the fused ideal):

      dgrad:  pb[m, t*K:(t+1)*K] = dyp shifted by tap t
              dx = pb @ wd                 (M x 9K) @ (9K x C)
      wgrad:  pb[m, t*C:(t+1)*C] = xp shifted by tap t   (buffer REUSED)
              dw = pb^T @ dy               (9C x M) @ (M x K)

    ``wd`` is the pre-rotated weight (flip + transpose to (9K, C)),
    prepared by XLA outside the kernel.  Large contraction dims (9K, M)
    keep the MXU busy; fp32 accumulation via preferred_element_type; dw
    accumulates across the sequential batch-chunk grid in fp32 scratch.
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dwa[...] = jnp.zeros_like(dwa)

    xp[...] = jnp.zeros_like(xp)
    dyp[...] = jnp.zeros_like(dyp)
    xp[:, 1:H + 1, 1:W + 1, :] = x_ref[...]
    dyp[:, 1:H + 1, 1:W + 1, :] = dy_ref[...]

    dyv = dy_ref[...]
    BN = dyv.shape[0]
    K = dyv.shape[3]
    C = x_ref.shape[3]
    m = BN * H * W

    # ---- dgrad: im2col dy (tap t=(tr,ts) reads dyp[a+tr, b+ts], which is
    # dy[a+1-r, b+1-s] for r=2-tr, s=2-ts — wd's rows are ordered to match)
    for tr in range(3):
        for ts in range(3):
            t = tr * 3 + ts
            pb[:, :, :, t * K:(t + 1) * K] = dyp[:, tr:tr + H, ts:ts + W, :]
    dx = lax.dot_general(
        pb[...].reshape(m, pb.shape[3])[:, :9 * K], wd_ref[...],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # (M, C)
    dx_ref[...] = dx.reshape(BN, H, W, C).astype(dx_ref.dtype)

    # ---- wgrad: im2col x into the SAME buffer (lanes sized max(9C, 9K))
    for tr in range(3):
        for ts in range(3):
            t = tr * 3 + ts
            pb[:, :, :, t * C:(t + 1) * C] = xp[:, tr:tr + H, ts:ts + W, :]
    dwa[...] += lax.dot_general(
        pb[...].reshape(m, pb.shape[3])[:, :9 * C], dyv.reshape(m, K),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # (9C, K)

    @pl.when(i == pl.num_programs(0) - 1)
    def _flush():
        dw_ref[...] = dwa[...].reshape(3, 3, C, K).astype(dw_ref.dtype)


def conv3x3_bwd(x, dy, w, *, block_n=None, interpret=None):
    """Fused backward of ``y = conv3x3_s1_same(x, w)`` (NHWC / HWIO).

    Returns ``(dx, dw)``; reads x and dy from HBM exactly once each.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    N, H, W, C = x.shape
    K = w.shape[3]
    assert w.shape == (3, 3, C, K), w.shape
    assert dy.shape == (N, H, W, K), dy.shape
    if interpret is None:
        interpret = _interpret()
    bn = block_n or _auto_block_n(N, H, W, C, K, x.dtype.itemsize)
    assert N % bn == 0, "block_n=%d must divide N=%d" % (bn, N)
    grid = (N // bn,)
    # pre-rotate the weight for the single dgrad matmul: wd[(tr*3+ts)*K+k,
    # c] = w[2-tr, 2-ts, c, k] (XLA does this once; it is 9*C*K elements)
    wd = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2).reshape(9 * K, C)
    kernel = functools.partial(_conv_bwd_kernel, H=H, W=W)
    dx, dw = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((N, H, W, C), x.dtype),
                   jax.ShapeDtypeStruct((3, 3, C, K), w.dtype)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bn, H, W, C), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bn, H, W, K), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9 * K, C), lambda i: (0, 0)),
        ],
        out_specs=(pl.BlockSpec((bn, H, W, C), lambda i: (i, 0, 0, 0)),
                   pl.BlockSpec((3, 3, C, K), lambda i: (0, 0, 0, 0))),
        scratch_shapes=[pltpu.VMEM((bn, H + 2, W + 2, C), x.dtype),
                        pltpu.VMEM((bn, H + 2, W + 2, K), dy.dtype),
                        pltpu.VMEM((bn, H, W, 9 * max(C, K)), x.dtype),
                        pltpu.VMEM((9 * C, K), jnp.float32)],
        interpret=interpret,
    )(x, dy, wd)
    return dx, dw


def conv3x3_bytes(x_shape, k):
    """Fused-ideal HBM bytes for the backward: |x| + |dy| + |dx| + |dw|."""
    n, h, w, c = x_shape
    act = n * h * w
    return 2 * (act * c + act * k + act * c) + 2 * 9 * c * k


# ------------------------------------------------------------ custom_vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=())
def conv3x3_s1(x, w):
    """3x3/s1/SAME NHWC conv whose BACKWARD is the fused Pallas kernel.

    Forward is XLA's conv (already bandwidth-optimal); backward replaces
    XLA's dgrad+wgrad pair (the 2.7x byte inflation) with ``conv3x3_bwd``.
    """
    return _conv_fwd_ref(x, w)


def _conv_fwd_ref(x, w):
    dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))
    return lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=[(1, 1), (1, 1)],
        dimension_numbers=dn)


def _conv_fwd(x, w):
    return _conv_fwd_ref(x, w), (x, w)


def _conv_bwd_rule(res, dy):
    x, w = res
    if conv3x3_bwd_legal(x.shape, w.shape, itemsize=x.dtype.itemsize):
        return conv3x3_bwd(x, dy, w)
    # XLA fallback for off-TPU / odd shapes
    _, vjp = jax.vjp(_conv_fwd_ref, x, w)
    return vjp(dy)


conv3x3_s1.defvjp(_conv_fwd, _conv_bwd_rule)
