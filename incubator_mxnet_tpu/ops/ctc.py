"""CTC loss — log-space alpha recursion over a lax.scan
(ref src/operator/nn/ctc_loss.cc / 3rdparty warp-ctc semantics).

TPU-native: the whole forward DP is one scan over time with static shapes
(the extended blank-interleaved label sequence is padded to 2L+1); the
backward pass is jax autodiff through the scan — no hand-written beta
recursion needed, and the (T, N, 2L+1) alpha lattice never materializes in
HBM beyond the scan carry.

Contract (matching the reference op):
- x: (T, N, C) UNNORMALIZED activations (softmax applied internally)
- labels: (N, L) float/int; entries < 0 are padding when label lengths are
  not given explicitly
- blank is class 0 ("first", the reference default) or C-1 ("last")
- returns per-sample NEGATIVE log likelihood (N,)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ctc_loss"]

_NEG = -1e30  # -inf stand-in that stays NaN-free through logsumexp


def _lse(*xs):
    m = xs[0]
    for x in xs[1:]:
        m = jnp.maximum(m, x)
    s = sum(jnp.exp(x - m) for x in xs)
    return m + jnp.log(jnp.maximum(s, 1e-37))


def ctc_loss(x, labels, data_lengths=None, label_lengths=None,
             blank_label="first"):
    T, N, C = x.shape
    L = labels.shape[1]
    S = 2 * L + 1
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)  # (T,N,C)
    labels = labels.astype(jnp.int32)

    if label_lengths is None:
        ll = jnp.sum((labels >= 0).astype(jnp.int32), axis=1)   # (N,)
    else:
        ll = label_lengths.astype(jnp.int32)
    if data_lengths is None:
        dl = jnp.full((N,), T, jnp.int32)
    else:
        dl = data_lengths.astype(jnp.int32)

    blank = 0 if blank_label == "first" else C - 1
    safe_labels = jnp.where(labels >= 0, labels, blank)

    # extended sequence: blank, l1, blank, l2, ..., blank  (N, S)
    pos = jnp.arange(S)
    is_lab = (pos % 2 == 1)
    lab_idx = jnp.minimum(pos // 2, L - 1)
    ext = jnp.where(is_lab[None, :], safe_labels[:, lab_idx], blank)  # (N,S)
    # valid extended positions: s < 2*ll+1
    valid = pos[None, :] < (2 * ll + 1)[:, None]                      # (N,S)

    # skip-transition allowed at s when ext[s] != blank and ext[s]!=ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((N, 2), -1, jnp.int32),
                              ext[:, :-2]], axis=1)
    can_skip = is_lab[None, :] & (ext != ext_m2)                      # (N,S)

    batch = jnp.arange(N)

    def emit(t_logp):  # (N,C) -> (N,S) log prob of each extended symbol
        return t_logp[batch[:, None], ext]

    alpha0 = jnp.full((N, S), _NEG, jnp.float32)
    e0 = emit(logp[0])
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(ll > 0, e0[:, 1], _NEG))

    def step(alpha, t_and_logp):
        t, lp = t_and_logp
        prev1 = jnp.concatenate(
            [jnp.full((N, 1), _NEG, jnp.float32), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((N, 2), _NEG, jnp.float32), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, _NEG)
        a = _lse(alpha, prev1, prev2) + emit(lp)
        a = jnp.where(valid, a, _NEG)
        # past this sample's data length the lattice freezes
        live = (t < dl)[:, None]
        a = jnp.where(live, a, alpha)
        return a, None

    ts = jnp.arange(1, T)
    alpha, _ = lax.scan(step, alpha0, (ts, logp[1:]))

    # NLL = -logsumexp(alpha[2*ll], alpha[2*ll-1]) at each sample's end
    end = 2 * ll
    a_end = alpha[batch, jnp.clip(end, 0, S - 1)]
    a_end1 = jnp.where(ll > 0,
                       alpha[batch, jnp.clip(end - 1, 0, S - 1)], _NEG)
    # (ll == 0 degenerates correctly: end = 0 is the all-blank path)
    return (-_lse(a_end, a_end1)).astype(jnp.float32)
