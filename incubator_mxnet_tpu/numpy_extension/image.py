"""npx.image — the reference's _npx__image_* op family
(ref src/operator/image/image_random.cc, resize.cc, crop.cc; exposed as
mx.npx.image.*). Operates on HWC (or NHWC-batched) mx.np arrays; the
random_* variants draw from the framework PRNG stream so runs are
reproducible under npx.random.seed."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray import NDArray
from ..numpy import ndarray as np_ndarray

from ..ndarray.random import _next_key as _key

__all__ = ["to_tensor", "normalize", "resize", "crop", "flip_left_right",
           "flip_top_bottom", "random_flip_left_right",
           "random_flip_top_bottom", "random_brightness", "random_contrast",
           "random_saturation", "random_hue", "random_color_jitter",
           "random_lighting", "adjust_lighting"]

#: ITU-R BT.601 luma weights (the reference's saturation/gray path)
_LUMA = (0.299, 0.587, 0.114)


def _data(x):
    return x._data if isinstance(x, NDArray) else jnp.asarray(x)


def _out(v):
    return np_ndarray(v)


def to_tensor(data):
    """HWC uint8 [0,255] → CHW float32 [0,1] (ref image_random.cc ToTensor);
    batched NHWC → NCHW."""
    x = _data(data).astype(jnp.float32) / 255.0
    perm = (2, 0, 1) if x.ndim == 3 else (0, 3, 1, 2)
    return _out(x.transpose(perm))


def normalize(data, mean=0.0, std=1.0):
    """CHW (or NCHW) channel-wise (x - mean) / std (ref Normalize)."""
    x = _data(data)
    c = x.shape[0] if x.ndim == 3 else x.shape[1]
    shp = (c, 1, 1) if x.ndim == 3 else (1, c, 1, 1)
    m = jnp.asarray(mean, jnp.float32).reshape(-1)[:c].reshape(shp) \
        if jnp.ndim(jnp.asarray(mean)) else jnp.asarray(mean)
    s = jnp.asarray(std, jnp.float32).reshape(-1)[:c].reshape(shp) \
        if jnp.ndim(jnp.asarray(std)) else jnp.asarray(std)
    return _out((x - m) / s)


def resize(data, size, keep_ratio=False, interp=1):
    """HWC bilinear/nearest resize (ref resize.cc); size int or (w, h).
    keep_ratio with an int size resizes the SHORTER edge to size and
    scales the other proportionally (reference semantics)."""
    x = _data(data)
    if isinstance(size, int):
        if keep_ratio:
            h0, w0 = (x.shape[0], x.shape[1]) if x.ndim == 3 \
                else (x.shape[1], x.shape[2])
            if h0 <= w0:
                h, w = size, max(1, round(w0 * size / h0))
            else:
                w, h = size, max(1, round(h0 * size / w0))
        else:
            w = h = size
    else:
        w, h = size
    method = "nearest" if interp == 0 else "bilinear"
    if x.ndim == 3:
        out = jax.image.resize(x.astype(jnp.float32), (h, w, x.shape[2]),
                               method)
    else:
        out = jax.image.resize(x.astype(jnp.float32),
                               (x.shape[0], h, w, x.shape[3]), method)
    return _out(out.astype(x.dtype) if x.dtype != jnp.float32 else out)


def crop(data, x, y, width, height):
    """HWC spatial crop at (x, y) (ref crop.cc)."""
    a = _data(data)
    if a.ndim == 3:
        return _out(a[y:y + height, x:x + width, :])
    return _out(a[:, y:y + height, x:x + width, :])


def flip_left_right(data):
    a = _data(data)
    return _out(jnp.flip(a, axis=-2))


def flip_top_bottom(data):
    a = _data(data)
    return _out(jnp.flip(a, axis=-3))


def _bernoulli():
    return jax.random.bernoulli(_key())


def random_flip_left_right(data):
    a = _data(data)
    return _out(jnp.where(_bernoulli(), jnp.flip(a, axis=-2), a))


def random_flip_top_bottom(data):
    a = _data(data)
    return _out(jnp.where(_bernoulli(), jnp.flip(a, axis=-3), a))


def _unit_draw(lo, hi):
    return jax.random.uniform(_key(), (), minval=lo, maxval=hi)


def random_brightness(data, min_factor, max_factor):
    a = _data(data).astype(jnp.float32)
    return _out(a * _unit_draw(min_factor, max_factor))


def random_contrast(data, min_factor, max_factor):
    a = _data(data).astype(jnp.float32)
    f = _unit_draw(min_factor, max_factor)
    gray = (a * jnp.asarray(_LUMA)).sum(axis=-1, keepdims=True)
    return _out(a * f + gray.mean(axis=(-3, -2), keepdims=True) * (1 - f))


def random_saturation(data, min_factor, max_factor):
    a = _data(data).astype(jnp.float32)
    f = _unit_draw(min_factor, max_factor)
    gray = (a * jnp.asarray(_LUMA)).sum(axis=-1, keepdims=True)
    return _out(a * f + gray * (1 - f))


def random_hue(data, min_factor, max_factor):
    """YIQ-rotation hue jitter (ref image_random.cc RandomHue)."""
    a = _data(data).astype(jnp.float32)
    alpha = _unit_draw(min_factor, max_factor)
    u, w = jnp.cos(alpha * jnp.pi), jnp.sin(alpha * jnp.pi)
    t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]])
    t_rgb = jnp.asarray([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]])
    one, zero = jnp.ones(()), jnp.zeros(())
    rot = jnp.stack([jnp.stack([one, zero, zero]),
                     jnp.stack([zero, u, -w]),
                     jnp.stack([zero, w, u])])
    m = t_rgb @ rot @ t_yiq
    return _out(a @ m.T)


def random_color_jitter(data, brightness=0.0, contrast=0.0, saturation=0.0,
                        hue=0.0):
    out = data
    if brightness:
        out = random_brightness(out, 1 - brightness, 1 + brightness)
    if contrast:
        out = random_contrast(out, 1 - contrast, 1 + contrast)
    if saturation:
        out = random_saturation(out, 1 - saturation, 1 + saturation)
    if hue:
        out = random_hue(out, -hue, hue)
    return out


def adjust_lighting(data, alpha):
    """AlexNet-style PCA lighting with fixed eigen basis
    (ref image_random.cc AdjustLighting)."""
    a = _data(data).astype(jnp.float32)
    eigval = jnp.asarray([55.46, 4.794, 1.148])
    eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]])
    delta = eigvec @ (jnp.asarray(alpha) * eigval)
    return _out(a + delta)


def random_lighting(data, alpha_std=0.05):
    alpha = alpha_std * jax.random.normal(_key(), (3,))
    return adjust_lighting(data, alpha)
