"""npx — mx.numpy_extension (ref python/mxnet/numpy_extension/):
neural-net ops usable on mx.np arrays + np-mode switches."""
from __future__ import annotations

from .. import ndarray as _nd
from ..numpy import ndarray as np_ndarray, _apply_np, _to
from ..util import set_np, reset_np, is_np_array, use_np
from ..context import cpu, gpu, tpu, num_gpus, num_tpus, current_context

__all__ = ["set_np", "reset_np", "is_np_array", "use_np", "cpu", "gpu", "tpu",
           "num_gpus", "num_tpus", "current_context", "relu", "sigmoid",
           "softmax", "log_softmax", "activation", "batch_norm", "layer_norm",
           "fully_connected", "convolution", "pooling", "dropout", "one_hot",
           "pick", "topk", "embedding", "gamma", "reshape_like", "waitall",
           "seed"]


def _wrap(nd_fn):
    def op(*args, **kwargs):
        out = nd_fn(*args, **kwargs)
        # re-class IN PLACE: constructing fresh np_ndarrays here would cut
        # the autograd tape (backward is keyed by output object identity).
        # Identity-returning ops (e.g. eval-mode Dropout) hand back an INPUT
        # object — re-classing that would corrupt the caller's array, so
        # route it through a taped identity first.
        def reclass(o):
            if any(o is a for a in args):
                o = _apply_np(lambda x: x, o)
            o.__class__ = np_ndarray
            return o

        if isinstance(out, (list, tuple)):
            return type(out)(reclass(o) for o in out)
        return reclass(out)
    return op


relu = _wrap(_nd.relu)
sigmoid = _wrap(_nd.sigmoid)
softmax = _wrap(_nd.softmax)
log_softmax = _wrap(_nd.log_softmax)
activation = _wrap(_nd.Activation)
batch_norm = _wrap(_nd.BatchNorm)
layer_norm = _wrap(_nd.LayerNorm)
fully_connected = _wrap(_nd.FullyConnected)
convolution = _wrap(_nd.Convolution)
pooling = _wrap(_nd.Pooling)
dropout = _wrap(_nd.Dropout)
one_hot = _wrap(_nd.one_hot)
pick = _wrap(_nd.pick)
topk = _wrap(_nd.topk)
embedding = _wrap(_nd.Embedding)
gamma = _wrap(_nd.gamma)
reshape_like = _wrap(_nd.reshape_like)
waitall = _nd.waitall


def seed(s):
    from ..ndarray import random as _r
    _r.seed(s)
