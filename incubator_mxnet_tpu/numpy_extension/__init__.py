"""npx — mx.numpy_extension (ref python/mxnet/numpy_extension/):
neural-net ops usable on mx.np arrays + np-mode switches + the npx image
and random sub-namespaces (ref _npx_* op registrations,
src/operator/numpy/*, numpy_extension/random.py, utils.py)."""
from __future__ import annotations

from .. import ndarray as _nd
from ..numpy import ndarray as np_ndarray, _apply_np, _to
from ..util import set_np, reset_np, is_np_array, is_np_shape, use_np
from ..context import cpu, gpu, tpu, num_gpus, num_tpus, current_context

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape", "use_np",
           "cpu", "gpu", "tpu",
           "num_gpus", "num_tpus", "current_context", "relu", "sigmoid",
           "softmax", "log_softmax", "activation", "batch_norm", "layer_norm",
           "fully_connected", "convolution", "pooling", "dropout", "one_hot",
           "pick", "topk", "embedding", "gamma", "reshape_like", "waitall",
           "seed",
           # round-5 breadth: the remaining _npx_* op registrations
           "arange_like", "batch_dot", "batch_flatten", "cast",
           "deconvolution", "erf", "erfinv", "gammaln", "gather_nd",
           "leaky_relu", "multibox_detection", "multibox_prior",
           "multibox_target", "rnn", "roi_pooling", "sequence_mask",
           "shape_array", "slice", "smooth_l1", "save", "load",
           "image", "random"]


def _wrap(nd_fn):
    def op(*args, **kwargs):
        out = nd_fn(*args, **kwargs)
        # re-class IN PLACE: constructing fresh np_ndarrays here would cut
        # the autograd tape (backward is keyed by output object identity).
        # Identity-returning ops (e.g. eval-mode Dropout) hand back an INPUT
        # object — re-classing that would corrupt the caller's array, so
        # route it through a taped identity first.
        def reclass(o):
            if any(o is a for a in args):
                o = _apply_np(lambda x: x, o)
            o.__class__ = np_ndarray
            return o

        if isinstance(out, (list, tuple)):
            return type(out)(reclass(o) for o in out)
        return reclass(out)
    return op


relu = _wrap(_nd.relu)
sigmoid = _wrap(_nd.sigmoid)
softmax = _wrap(_nd.softmax)
log_softmax = _wrap(_nd.log_softmax)
activation = _wrap(_nd.Activation)
batch_norm = _wrap(_nd.BatchNorm)
layer_norm = _wrap(_nd.LayerNorm)
fully_connected = _wrap(_nd.FullyConnected)
convolution = _wrap(_nd.Convolution)
pooling = _wrap(_nd.Pooling)
dropout = _wrap(_nd.Dropout)
one_hot = _wrap(_nd.one_hot)
pick = _wrap(_nd.pick)
topk = _wrap(_nd.topk)
embedding = _wrap(_nd.Embedding)
gamma = _wrap(_nd.gamma)
reshape_like = _wrap(_nd.reshape_like)
waitall = _nd.waitall

# remaining _npx_* op surface (ref src/operator contrib registrations)
arange_like = _wrap(_nd.arange_like)
batch_dot = _wrap(_nd.batch_dot)
# _npx_batch_flatten keeps MXNet semantics (N, prod(rest)) — must NOT
# route through nd.flatten, which delegates to the .flatten METHOD and
# would pick up np_ndarray's numpy-ravel override
batch_flatten = _wrap(lambda x: x.reshape((x.shape[0], -1)))
cast = _wrap(_nd.cast)
deconvolution = _wrap(_nd.Deconvolution)
erf = _wrap(_nd.erf)
erfinv = _wrap(_nd.erfinv)
gammaln = _wrap(_nd.gammaln)
gather_nd = _wrap(_nd.gather_nd)
leaky_relu = _wrap(_nd.LeakyReLU)
rnn = _wrap(_nd.RNN)
roi_pooling = _wrap(_nd.ROIPooling)
sequence_mask = _wrap(_nd.sequence_mask)
shape_array = _wrap(_nd.shape_array)
slice = _wrap(_nd.slice)   # noqa: A001  (ref _npx_slice)
smooth_l1 = _wrap(_nd.smooth_l1)


def _contrib_wrap(name):
    from ..ndarray import contrib as _c
    return _wrap(getattr(_c, name))


multibox_prior = _contrib_wrap("MultiBoxPrior")
multibox_target = _contrib_wrap("MultiBoxTarget")
multibox_detection = _contrib_wrap("MultiBoxDetection")


def save(file, arr):
    """ref numpy_extension/utils.py save — np arrays to a .npz-style file."""
    arrs = arr if isinstance(arr, (list, tuple, dict)) else [arr]
    _nd.save(file, arrs)


def load(file):
    """ref numpy_extension/utils.py load — returns np-ndarray payloads."""
    out = _nd.load(file)

    def reclass(o):
        o.__class__ = np_ndarray
        return o
    if isinstance(out, dict):
        return {k: reclass(v) for k, v in out.items()}
    return [reclass(v) for v in out]


def seed(s):
    from ..ndarray import random as _r
    _r.seed(s)


from . import image  # noqa: E402  (npx.image.* op namespace)
from . import random  # noqa: E402  (npx.random: bernoulli/normal_n/uniform_n)
