"""npx.random (ref python/mxnet/numpy_extension/random.py:
seed / bernoulli / normal_n / uniform_n)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray import NDArray
from ..ndarray.random import _next_key as _key
from ..numpy import ndarray as np_ndarray

__all__ = ["seed", "bernoulli", "normal_n", "uniform_n"]


def seed(s):
    from ..ndarray import random as _r
    _r.seed(s)


def bernoulli(prob=None, logit=None, size=None, dtype="float32", **kw):
    """Draws with P(1) = prob, or sigmoid(logit) when given logits
    (exactly one of prob/logit, as the reference enforces)."""
    if (prob is None) == (logit is None):
        raise ValueError("pass exactly one of prob / logit")
    if prob is None:
        prob = jax.nn.sigmoid(logit._data if isinstance(logit, NDArray)
                              else jnp.asarray(logit))
    elif isinstance(prob, NDArray):
        prob = prob._data
    shp = size if isinstance(size, tuple) else \
        ((size,) if size is not None else jnp.shape(prob))
    return np_ndarray(jax.random.bernoulli(_key(), prob, shp).astype(dtype))


def _batch_shape(batch_shape):
    if batch_shape is None:
        return ()
    return batch_shape if isinstance(batch_shape, tuple) else (batch_shape,)


def normal_n(loc=0.0, scale=1.0, batch_shape=None, dtype="float32", **kw):
    """ref npx.random.normal_n: batch_shape PREPENDS to the broadcast
    param shape (n independent draws per parameter setting)."""
    loc_ = loc._data if isinstance(loc, NDArray) else jnp.asarray(loc)
    scale_ = scale._data if isinstance(scale, NDArray) else jnp.asarray(scale)
    pshape = jnp.broadcast_shapes(jnp.shape(loc_), jnp.shape(scale_))
    shp = _batch_shape(batch_shape) + pshape
    return np_ndarray((loc_ + scale_ * jax.random.normal(_key(), shp))
                      .astype(dtype))


def uniform_n(low=0.0, high=1.0, batch_shape=None, dtype="float32", **kw):
    low_ = low._data if isinstance(low, NDArray) else jnp.asarray(low)
    high_ = high._data if isinstance(high, NDArray) else jnp.asarray(high)
    pshape = jnp.broadcast_shapes(jnp.shape(low_), jnp.shape(high_))
    shp = _batch_shape(batch_shape) + pshape
    u = jax.random.uniform(_key(), shp)
    return np_ndarray((low_ + (high_ - low_) * u).astype(dtype))
