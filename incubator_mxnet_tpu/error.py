"""Typed exceptions (ref python/mxnet/error.py).

The reference maps C++ error prefixes onto Python exception types via
register_error; here errors originate in Python/JAX, so the hierarchy exists
for API parity and for user code that catches the typed classes.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["MXNetError", "InternalError", "ValueError", "TypeError",
           "IndexError", "NotImplementedForSymbol", "register"]

_ERROR_REGISTRY = {}


def register(name_or_cls):
    """ref error.py register — map an error-prefix name to a class."""
    def do_register(cls, name):
        _ERROR_REGISTRY[name] = cls
        return cls
    if isinstance(name_or_cls, str):
        return lambda cls: do_register(cls, name_or_cls)
    return do_register(name_or_cls, name_or_cls.__name__)


@register
class InternalError(MXNetError):
    """Framework-internal invariant violation (ref error.py InternalError)."""


@register
class ValueError(MXNetError, ValueError):  # noqa: A001 — ref shadows builtins
    pass


@register
class TypeError(MXNetError, TypeError):  # noqa: A001
    pass


@register
class IndexError(MXNetError, IndexError):  # noqa: A001
    pass


class NotImplementedForSymbol(MXNetError):
    """ref base.py NotImplementedForSymbol — nd-only op called on a Symbol."""

    def __init__(self, function, alias=None, *args):
        super().__init__()
        self.function = function.__name__ if callable(function) else function

    def __str__(self):
        return "Function %s is not implemented for Symbol." % self.function
