"""Utility scopes & flags (ref python/mxnet/util.py: np_shape/np_array scopes)."""
from __future__ import annotations

import functools
import threading

__all__ = ["is_np_shape", "is_np_array", "set_np_shape", "set_np", "reset_np",
           "np_shape", "np_array", "use_np", "getenv", "setenv"]


class _Flags(threading.local):
    def __init__(self):
        self.np_shape = False
        self.np_array = False


_F = _Flags()


def is_np_shape():
    return _F.np_shape


def is_np_array():
    return _F.np_array


def set_np_shape(active):
    prev = _F.np_shape
    _F.np_shape = bool(active)
    return prev


def set_np(shape=True, array=True):
    """ref util.py set_np — enable NumPy semantics globally."""
    _F.np_shape = shape
    _F.np_array = array


def reset_np():
    set_np(False, False)


class _Scope:
    def __init__(self, attr, value):
        self.attr = attr
        self.value = value

    def __enter__(self):
        self.prev = getattr(_F, self.attr)
        setattr(_F, self.attr, self.value)
        return self

    def __exit__(self, *a):
        setattr(_F, self.attr, self.prev)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with type(self)(self.attr, self.value):
                return fn(*args, **kwargs)
        return wrapped


def np_shape(active=True):
    return _Scope("np_shape", active)


def np_array(active=True):
    return _Scope("np_array", active)


def use_np(fn):
    """ref util.py use_np decorator."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with _Scope("np_shape", True), _Scope("np_array", True):
            return fn(*args, **kwargs)
    return wrapped


def getenv(name):
    import os
    return os.environ.get(name)


def setenv(name, value):
    import os
    os.environ[name] = value
