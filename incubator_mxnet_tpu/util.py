"""Utility scopes & flags (ref python/mxnet/util.py: np_shape/np_array scopes)."""
from __future__ import annotations

import functools
import threading

__all__ = ["inspect_tensor",
           "is_np_shape", "is_np_array", "set_np_shape", "set_np", "reset_np",
           "np_shape", "np_array", "use_np", "getenv", "setenv"]


class _Flags(threading.local):
    def __init__(self):
        self.np_shape = False
        self.np_array = False


_F = _Flags()


def is_np_shape():
    return _F.np_shape


def is_np_array():
    return _F.np_array


def set_np_shape(active):
    prev = _F.np_shape
    _F.np_shape = bool(active)
    return prev


def set_np(shape=True, array=True):
    """ref util.py set_np — enable NumPy semantics globally."""
    _F.np_shape = shape
    _F.np_array = array


def reset_np():
    set_np(False, False)


class _Scope:
    def __init__(self, attr, value):
        self.attr = attr
        self.value = value

    def __enter__(self):
        self.prev = getattr(_F, self.attr)
        setattr(_F, self.attr, self.value)
        return self

    def __exit__(self, *a):
        setattr(_F, self.attr, self.prev)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with type(self)(self.attr, self.value):
                return fn(*args, **kwargs)
        return wrapped


def np_shape(active=True):
    return _Scope("np_shape", active)


def np_array(active=True):
    return _Scope("np_array", active)


def use_np(fn):
    """ref util.py use_np decorator."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with _Scope("np_shape", True), _Scope("np_array", True):
            return fn(*args, **kwargs)
    return wrapped


def getenv(name):
    import os
    return os.environ.get(name)


def setenv(name, value):
    import os
    os.environ[name] = value


def inspect_tensor(data, tag="", check_nan=True, check_inf=True,
                   dump_dir=None, logger=None):
    """Tensor debugging inspector (ref src/common/tensor_inspector.h
    TensorInspector::print_string/check_value/dump_to_file).

    Logs shape/dtype/min/max/mean/std and NaN/Inf counts for an NDArray (or
    numpy array); optionally dumps the value as ``<dump_dir>/<tag>.npy``.
    Returns the stats dict so tests/monitors can assert on it.
    """
    import logging as _logging
    import numpy as onp
    log = (logger or _logging).info if logger is not False else (lambda *a: None)
    a = data.asnumpy() if hasattr(data, "asnumpy") else onp.asarray(data)
    af = a.astype("float64") if a.dtype.kind in "fiu" else None
    stats = {"tag": tag, "shape": tuple(a.shape), "dtype": str(a.dtype)}
    if af is not None and af.size:
        stats.update({
            "min": float(onp.nanmin(af)), "max": float(onp.nanmax(af)),
            "mean": float(onp.nanmean(af)), "std": float(onp.nanstd(af)),
            "nan_count": int(onp.isnan(af).sum()) if check_nan else None,
            "inf_count": int(onp.isinf(af).sum()) if check_inf else None,
        })
    log("inspect[%s]: %s", tag, stats)
    if dump_dir is not None:
        import os
        os.makedirs(dump_dir, exist_ok=True)
        onp.save(os.path.join(dump_dir, "%s.npy" % (tag or "tensor")), a)
    return stats
