"""Typed environment-variable registry (ref docs/faq/env_var.md and the
dmlc::Parameter idiom — every knob declared, typed, and documented in ONE
place instead of scattered os.environ reads).

``describe()`` renders the registry (the env_var.md analog);
``get_env(name)`` is the typed accessor every subsystem uses.
"""
from __future__ import annotations

import os

__all__ = ["ENV_VARS", "get_env", "describe"]

ENV_VARS = {
    # name: (type, default, doc)
    "MXTPU_COORD_ADDR": (
        str, None,
        "jax.distributed coordinator host:port. Set by tools/launch.py; "
        "initialises the runtime at package import (multi-host DCN)."),
    "MXTPU_NUM_PROC": (
        int, 1, "Number of distributed worker processes (tools/launch.py)."),
    "MXTPU_PROC_ID": (
        int, 0, "This worker's process id in [0, MXTPU_NUM_PROC)."),
    "MXTPU_FLASH_INTERPRET": (
        bool, False,
        "Run the flash-attention Pallas kernels in interpret mode on CPU "
        "(CI/testing; ops/attention.py)."),
    "MXTPU_FLASH_FORCE": (
        bool, False,
        "Use the flash-attention kernels for every LEGAL shape, overriding "
        "the narrow-head (D<128) short-S profitability heuristic — opt in "
        "when the composite's B*H*S^2 score memory nears OOM."),
    "MXTPU_FLASH_BLOCK_Q": (
        int, 0,
        "Override the flash-attention q-block size (ops/attention.py). "
        "0 = auto (largest of 1024/512/256/128 dividing S; 1024 measured "
        "fastest on v5e at S>=8k for fwd+bwd). Must divide S."),
    "MXTPU_FLASH_BLOCK_K": (
        int, 0,
        "Override the flash-attention k-block size. 0 = auto. Must "
        "divide S."),
    "MXTPU_ASYNC_STALENESS": (
        int, 4,
        "dist_async staleness bound: pushes per key between cross-process "
        "parameter averages (kvstore.DistAsyncKVStore — the local-SGD "
        "analog of the reference's async parameter server)."),
    "MXTPU_INT8_SIM": (
        bool, False,
        "Force the fp32-simulated path for quantized matmul/conv instead "
        "of native int8 dot_general with int32 accumulation "
        "(ndarray/contrib.py quantized_* ops)."),
    "MXTPU_MATMUL_PRECISION": (
        str, None,
        "Matmul/conv precision on the MXU: 'default' (bf16 multiplies, "
        "fp32 accumulate — fastest), 'high' (3-pass), 'highest' (fp32). "
        "Applied at package import via jax_default_matmul_precision; the "
        "numerics sweep (test_utils.op_consistency_sweep) verifies "
        "CPU<->TPU agreement of matmul-class ops under 'highest'."),
    "MXTPU_NO_NATIVE": (
        bool, False,
        "Disable the native C++ library even if it builds (forces the "
        "pure-Python IO tiers)."),
    "MXTPU_PREDICT_LIB": (
        str, None,
        "Path to libmxtpu_predict.so for C/C++/Perl predict clients "
        "(cpp_package, perl_package); defaults to the loader path."),
    "MXTPU_PYTHON": (
        str, None,
        "Interpreter the embedded C predict API boots (c_predict_api.cc); "
        "defaults to the build-time python."),
    "MXTPU_KVSTORE_DEBUG": (
        int, 0,
        "Verbose logging in the kvstore server-role facade "
        "(kvstore_server.py)."),
    "MXTPU_ROLE": (
        str, "worker",
        "Process role for launch scripts that branch on it "
        "(kvstore_server._init_kvstore_server_module): 'worker' or "
        "'server'. DMLC_ROLE, when set, takes precedence (reference "
        "launcher compatibility)."),
    "MXTPU_EXEC_CACHE_SIZE": (
        int, 16,
        "Bound on each per-block hybridize() shape-keyed jit cache (the "
        "CachedOp analog); least-recently-dispatched entry is evicted "
        "past the bound. TrainStep/EvalStep/ServedModel executables "
        "moved to the shared AOT cache — size THAT with "
        "MXTPU_AOT_CACHE_SIZE (docs/AOT.md)."),
    "MXTPU_AOT_CACHE_SIZE": (
        int, 64,
        "Bound on the process-wide AOT compiled-executable cache "
        "(aot.CACHE — the shared replacement for the per-instance "
        "TrainStep/EvalStep/ServedModel caches). Eviction is LRU by "
        "last-dispatch time and each eviction increments "
        "mxtpu_aot_evictions_total; size it to hold every live "
        "(model, bucket, dtype) combination or post-warm traffic "
        "recompiles (docs/AOT.md)."),
    "MXTPU_AOT_CACHE_DIR": (
        str, None,
        "Directory for persisted jax.export (StableHLO) executables, one "
        "artifact per AOT cache key. A fresh process pointed here loads "
        "programs instead of re-tracing the Python model (artifact hit); "
        "unset disables the persistent layer. Artifacts are versioned by "
        "jax version + format version; train-kind programs are never "
        "persisted (docs/AOT.md)."),
    "MXTPU_AOT_PREWARM": (
        bool, True,
        "Pre-warm every configured batcher bucket of an incoming model "
        "version during ModelRegistry.load() hot-reloads (background "
        "thread, smallest bucket first so traffic cuts over early) so the "
        "swap never puts a compile window into request p99. Per-call "
        "override via load(prewarm=)."),
    "MXTPU_AOT_WARM_TIMEOUT_S": (
        float, 60.0,
        "Bound on how long ModelRegistry.load() blocks for the prewarm "
        "thread to finish compiling all buckets before returning anyway "
        "(the warm continues in the background; remaining buckets "
        "compile-on-first-dispatch as before)."),
    "MXTPU_NO_DONATE": (
        bool, False,
        "Disable input-buffer donation in the fused train/eval steps "
        "(jit.py). Donation updates parameters in place (kWriteInplace); "
        "turn off when debugging needs pre-step values alive."),
    "MXTPU_REMAT": (
        bool, False,
        "Default jax.checkpoint (rematerialisation) for TrainStep when the "
        "caller does not pass remat= explicitly — trades FLOPs for "
        "activation memory (MXNET_BACKWARD_DO_MIRROR analog)."),
    "MXTPU_ENGINE_BULK_SIZE": (
        int, 15,
        "Initial engine bulk size (MXNET_ENGINE_BULK_SIZE analog). "
        "Informational on TPU: XLA already compiles the whole step as one "
        "program; kept for API parity with engine.set_bulk_size."),
    "MXTPU_PROFILER_AUTOSTART": (
        bool, False,
        "Start the profiler at package import and dump on interpreter exit "
        "(MXNET_PROFILER_AUTOSTART analog)."),
    "MXTPU_PROFILER_FILENAME": (
        str, "profile.json",
        "Chrome-trace output path used by the autostarted profiler dump "
        "(MXNET_PROFILE_FILENAME analog; profiler.set_config overrides)."),
    "MXTPU_KVSTORE_BIGARRAY_BOUND": (
        int, 1000000,
        "Element-count bound above which a dense value gets its OWN host "
        "allgather instead of riding the per-dtype batched concat "
        "(MXNET_KVSTORE_BIGARRAY_BOUND analog — bounds peak host memory of "
        "the batch buffer)."),
    "MXTPU_P3_SLICE": (
        int, 1000000,
        "P3 slice bound in ELEMENTS for dist_async priority averaging "
        "(kvstore.DistAsyncKVStore._average_batch — ref p3store_dist.h "
        "slicing): no collective carries more than this many elements, so "
        "time-to-first-averaged-parameter is bounded by the slice, not "
        "the largest tensor."),
    "MXTPU_SERVE_MAX_BATCH": (
        int, 8,
        "Dynamic batcher dispatch bound (serving/batcher.py): a batch is "
        "dispatched when this many requests are waiting, or when "
        "MXTPU_SERVE_TIMEOUT_MS elapses after the first one. Match it to "
        "the batch axis the servable compiles best at (an exported .mxtpu "
        "artifact re-chunks buckets onto its one exported batch shape)."),
    "MXTPU_SERVE_TIMEOUT_MS": (
        float, 5.0,
        "Dynamic batcher coalescing window in milliseconds: the longest a "
        "request waits for companions before a partial batch is flushed. "
        "Raise to trade tail latency for bigger batches (TF-Serving "
        "batch_timeout_micros analog)."),
    "MXTPU_SERVE_QUEUE_SIZE": (
        int, 64,
        "PER-REPLICA bound on each model's serving dispatch queues "
        "(serving/batcher.py; total capacity = this x MXTPU_SERVE_REPLICAS)."
        " When every live replica's queue is full, submits reject with "
        "QueueFullError (HTTP 429) — explicit backpressure instead of "
        "unbounded latency; /healthz reports degraded at >= 80% aggregate "
        "occupancy."),
    "MXTPU_SERVE_REPLICAS": (
        int, 1,
        "Data-parallel replica executors per served model "
        "(serving/batcher.py): each replica owns a bounded dispatch queue "
        "and worker thread, fed by a least-depth router in submit(), so "
        "aggregate goodput scales with chips. Replica-aware servables "
        "(ServedModel, MeshServable) pin each replica's executable to its "
        "own device; a dead replica worker drains back through the router "
        "and /healthz reports degraded. Per-model override via "
        "load(replicas=) at first load (docs/SERVING.md)."),
    "MXTPU_SERVE_TP": (
        int, 1,
        "Default tensor-parallel degree for serving.sharded.MeshServable "
        "when no mesh is passed: weights shard over a 'tp' mesh axis of "
        "this size via jax.sharding.NamedSharding (GSPMD inserts the "
        "collectives), for models too big for one chip. 1 = single-device "
        "predict (docs/SERVING.md)."),
    "MXTPU_SERVE_DEADLINE_MS": (
        float, None,
        "Default per-request serving deadline in milliseconds: requests "
        "still queued when it passes fail with DeadlineExceededError "
        "(HTTP 504) instead of dispatching stale work. None = no deadline; "
        "a request's own deadline_ms overrides."),
    "MXTPU_SERVE_PORT": (
        int, 8080,
        "Default port for serving.ServingServer's HTTP front-end "
        "(serving/server.py); 0 picks an ephemeral port (tests)."),
    "MXTPU_FAULTLAB": (
        str, None,
        "Faultlab arming spec applied at import (telemetry/faultlab.py): "
        "';'-separated 'site:kind[:key=value...]' entries, kind in "
        "{exception, replica_kill, slow_ms, kv_oom, nan_poison, "
        "artifact_corrupt}, keys stride=/p=/seed=/budget=/ms=. Unset = "
        "disarmed (hot-path fault points are near-zero-cost no-ops). "
        "Runtime arming via POST /debug/faults (docs/RESILIENCE.md)."),
    "MXTPU_RESILIENCE_RETRY": (
        bool, True,
        "Single bounded retry of idempotent predict requests that failed "
        "because their replica worker died (serving/resilience.py): the "
        "request re-enters the router once, still under its original "
        "deadline; a second death fails it. Counted on "
        "mxtpu_retries_total{model}. Off = replica death fails the batch "
        "immediately."),
    "MXTPU_RESILIENCE_ROLLBACK": (
        bool, True,
        "Last-known-good rollback (serving/registry.py): when a live "
        "version flips to degraded (shadow breach, numerics storm, "
        "hlolint refusal) and a previous healthy version is still "
        "resident, repoint to it instead of serving degraded — flightrec "
        "'rolled_back_to' + sticky describe() provenance. Off = degraded "
        "is sticky until a human reloads (pre-resilience behavior)."),
    "MXTPU_RESILIENCE_BACKOFF_BASE_S": (
        float, 0.1,
        "Supervisor respawn backoff base in seconds "
        "(serving/resilience.py): the Nth consecutive death of a replica "
        "waits base * 2^(N-1) (+ seeded jitter) before respawn, capped at "
        "MXTPU_RESILIENCE_BACKOFF_CAP_S."),
    "MXTPU_RESILIENCE_BACKOFF_CAP_S": (
        float, 5.0,
        "Upper bound on the supervisor's exponential respawn backoff."),
    "MXTPU_RESILIENCE_CRASH_N": (
        int, 5,
        "Crash-loop circuit breaker: a replica that dies this many times "
        "within MXTPU_RESILIENCE_CRASH_WINDOW_S is PARKED (no further "
        "respawns, flightrec 'replica_parked', /healthz degraded) instead "
        "of being respawned into the same crash."),
    "MXTPU_RESILIENCE_CRASH_WINDOW_S": (
        float, 30.0,
        "Sliding window in seconds for the crash-loop circuit breaker's "
        "death count (MXTPU_RESILIENCE_CRASH_N)."),
    "MXTPU_RESILIENCE_POLL_S": (
        float, 0.05,
        "Supervisor poll interval in seconds (serving/resilience.py): how "
        "often dead replicas / dead decode loops are scanned for. The "
        "floor on detection latency; respawn timing adds the backoff."),
    "MXTPU_TELEMETRY_FLUSH_S": (
        float, 0.0,
        "Periodic telemetry flush interval in seconds (telemetry package): "
        "> 0 starts a daemon thread at package import that writes the full "
        "Prometheus exposition to MXTPU_TELEMETRY_FILE every interval — "
        "how headless training jobs emit metrics without the HTTP server. "
        "0 disables (telemetry.start_periodic_flush() still works)."),
    "MXTPU_TELEMETRY_FILE": (
        str, "telemetry.prom",
        "Path the periodic telemetry flusher writes (atomic tmp+rename; "
        "node-exporter textfile-collector compatible)."),
    "MXTPU_TELEMETRY_MAX_SERIES": (
        int, 64,
        "Per-metric bound on distinct label combinations in the telemetry "
        "registry. Past the bound, new label values are clamped onto the "
        "'_other_' series with a one-time RuntimeWarning — unbounded label "
        "cardinality (request ids) must never OOM the process."),
    "MXTPU_SPANS_BUFFER": (
        int, 8192,
        "Bound on the finished-span ring buffer (telemetry/spans.py): "
        "oldest spans age out past it. The buffer backs GET /debug/spans "
        "and spans.export_jsonl()/dump_jsonl()."),
    "MXTPU_SPANS_HISTOGRAM": (
        bool, False,
        "Opt-in bridge feeding every finished span's duration into the "
        "mxtpu_span_seconds{span=<name>} histogram on the shared registry "
        "(spans.set_histogram_bridge overrides at runtime). Off by "
        "default: per-span observe() is only worth paying for when "
        "something scrapes the histogram."),
    "MXTPU_FLIGHTREC_SIZE": (
        int, 2048,
        "Bound on the flight-recorder event ring "
        "(telemetry/flightrec.py): step/compile/dispatch/io/kvstore phase "
        "events, oldest aged out — the black-box tape dumped on crashes, "
        "stalls, and GET /debug/flightrec."),
    "MXTPU_FLIGHTREC_FILE": (
        str, "flightrec.jsonl",
        "Path the flight recorder writes its JSONL tape to on unhandled "
        "exceptions (install_crash_dump) and flightrec.dump()."),
    "MXTPU_FLIGHTREC_DUMP_ON_CRASH": (
        bool, True,
        "Dump the flight-recorder tape to MXTPU_FLIGHTREC_FILE when an "
        "unhandled exception kills the main thread or a worker thread "
        "(sys/threading excepthook chain installed at package import). "
        "Only fires when the tape is non-empty."),
    "MXTPU_HLOLINT_GATE": (
        bool, True,
        "Lint freshly prewarmed serve/eval AOT artifacts (tools/hlolint "
        "H-rules over the persisted StableHLO modules) inside "
        "ModelRegistry.load()'s warm path, BEFORE dispatch cuts over to "
        "the incoming version: error-severity findings (fp64 leak, host "
        "round-trip, predicted HBM overrun, corrupt artifact) refuse the "
        "cutover with a degraded reason in describe()/health(); warns "
        "land in flightrec + mxtpu_hlolint_findings_total{rule}. Only "
        "artifacts are linted, so loads without MXTPU_AOT_CACHE_DIR (or "
        "without prewarm) skip the gate (docs/STATIC_ANALYSIS.md)."),
    "MXTPU_HLOLINT_HBM_BUDGET": (
        float, None,
        "Per-device HBM budget in BYTES the hlolint H004 rule compares "
        "each artifact's header peak_bytes (memory_analysis, persisted "
        "at export) against — a program predicted to overrun is rejected "
        "before deploy instead of OOMing after cutover. Unset: the "
        "devstats per-device-kind capacity table "
        "(telemetry/devstats.py hbm_capacity()); backends the table "
        "doesn't know (CPU) skip H004 entirely."),
    "MXTPU_HLODIFF_GATE": (
        bool, True,
        "Diff freshly prewarmed AOT artifacts against the currently "
        "ROUTED version's programs (tools/hlodiff D-rules, matched per "
        "(kind, bucket, mesh_sig)) inside ModelRegistry.load()'s warm "
        "path, AFTER the hlolint pass: error-severity findings (D001 "
        "FLOPs growth / D003 donation regression on serve-/decode-kind "
        "programs) refuse the cutover with degraded reason "
        "hlodiff:<rule> and ride the last-known-good rollback; warns "
        "land in flightrec + mxtpu_hlodiff_findings_total{rule}. First "
        "loads (no routed reference) and byte-identical redeploys "
        "(cache hit, nothing fresh) skip the diff "
        "(docs/STATIC_ANALYSIS.md)."),
    "MXTPU_HLODIFF_FLOPS_TOL": (
        float, 0.1,
        "hlodiff D001 tolerance: flag a candidate program whose header "
        "FLOPs (cost_analysis, persisted at export) exceed its base "
        "program's by more than this fraction (0.1 = +10%). On "
        "serve-/decode-kind artifacts the finding is error severity and "
        "the deploy gate refuses the cutover."),
    "MXTPU_HLODIFF_PEAK_TOL": (
        float, 0.1,
        "hlodiff D002 tolerance: flag a candidate program whose header "
        "peak_bytes (memory_analysis) exceed its base program's by more "
        "than this fraction (0.1 = +10%) — predicted HBM headroom "
        "shrinking deploy over deploy ends in H004/OOM; warn severity."),
    "MXTPU_HLOLINT_PAD_WASTE": (
        float, 0.5,
        "hlolint H005 threshold: flag a compiled shape bucket whose "
        "worst-fit padded batch wastes more than this fraction of its "
        "compute relative to the next smaller compiled bucket "
        "((b - (b'+1))/b across the artifact set's bucket ladder). The "
        "default 0.5 keeps power-of-two ladders (worst case 37.5%) "
        "clean and fires on gap-toothed ladders like {1, 64}."),
    "MXTPU_GEN_BLOCK_SIZE": (
        int, 16,
        "Token slots per KV-cache block (ops/kvcache.py paged pool). "
        "Smaller blocks waste less tail capacity per sequence but grow "
        "the block tables; docs/GENERATE.md has the sizing math."),
    "MXTPU_GEN_KV_BLOCKS": (
        int, 256,
        "KV pool capacity in blocks, preallocated in HBM at engine "
        "construction (serving/generate.py). Admission of new sequences "
        "backpressures when the free list runs dry; size against "
        "devstats hbm_capacity() per docs/GENERATE.md."),
    "MXTPU_GEN_MAX_BATCH": (
        int, 8,
        "Upper decode-batch bucket of the continuous-batching loop (and "
        "the prefill batcher's max batch). The decode bucket ladder is "
        "powers of two up to this; every bucket is AOT-prewarmed so "
        "steady-state decode never compiles."),
    "MXTPU_GEN_PREFILL_LEN": (
        int, 64,
        "Fixed prompt shape of the compiled prefill programs: prompts "
        "are padded to this length (true length rides as data), longer "
        "ones are rejected 400. One shape keeps prefill on the bucketed "
        "batcher's handful of compiled programs."),
    "MXTPU_GEN_MAX_TOKENS": (
        int, 128,
        "Cap on max_new_tokens per generate request; also sizes the "
        "per-sequence block-table width (with MXTPU_GEN_PREFILL_LEN)."),
    "MXTPU_GEN_STEP_IDLE_MS": (
        float, 1.0,
        "Decode-loop sleep granularity when NO sequence is in flight "
        "(the loop never sleeps between steps while anything decodes)."),
    "MXTPU_GEN_SLO_INTER_TOKEN_MS": (
        float, None,
        "When set, each tenant generating on a model gets a "
        "<model>/inter_token/<tenant> SLO (telemetry/slo.py kind="
        "inter_token) fed one outcome per token gap against this "
        "threshold in ms — burn-rate alerts and /debug/slo rows per "
        "tenant. Unset: no inter-token objectives are minted."),
    "MXTPU_GEN_PREWARM": (
        bool, True,
        "AOT-compile (or artifact-load) every generative program bucket "
        "at engine construction and route fresh decode artifacts "
        "through the hlolint gate. Disable only in tests that assert "
        "compile-counting behavior."),
    "MXTPU_WATCHDOG": (
        bool, False,
        "Autostart the stall watchdog monitor thread at package import "
        "(telemetry/watchdog.py; watchdog.start()/stop() at runtime). "
        "Instrumented loops heartbeat regardless — the knob only controls "
        "the monitor."),
    "MXTPU_WATCHDOG_QUIET_S": (
        float, 60.0,
        "Default quiet period in seconds before a heartbeat channel "
        "(train step, batcher worker, io prefetch) is declared stalled "
        "and an all-thread stack + flight-recorder report is emitted — "
        "once per stall episode, process never killed. Per-channel "
        "override via watchdog.register(quiet_s=)."),
    "MXTPU_WATCHDOG_POLL_S": (
        float, 1.0,
        "Watchdog monitor poll interval in seconds (stall detection "
        "latency is quiet period + up to one poll)."),
    "MXTPU_WATCHDOG_FILE": (
        str, None,
        "File the watchdog APPENDS stall reports to (all-thread stacks + "
        "flight-recorder tail). None: reports go to logging.error and "
        "stay readable at watchdog.last_report() / GET /debug/stacks."),
    "MXTPU_DEVICE_PEAK_FLOPS": (
        float, None,
        "Override the per-chip peak FLOP/s the device-truth MFU gauges "
        "(mxtpu_device_mfu, telemetry/devstats.py) divide by. Unset: "
        "resolved from jax.devices()[0].device_kind via the built-in "
        "peak table; unknown kinds (CPU) fall back to a report-only "
        "nominal peak (docs/OBSERVABILITY.md 'Device truth')."),
    "MXTPU_DEVICE_PEAK_HBM_BPS": (
        float, None,
        "Override the per-chip peak HBM bytes/s the "
        "mxtpu_device_hbm_bw_util gauge divides by. Unset: device_kind "
        "table, else report-only fallback (telemetry/devstats.py)."),
    "MXTPU_DEVSTATS": (
        bool, False,
        "Autostart the device-memory sampler daemon at package import "
        "(telemetry/devstats.py; devstats.start()/stop() at runtime): "
        "polls device.memory_stats() into "
        "mxtpu_device_memory_bytes{device,stat} and files a flightrec "
        "hbm_pressure event at >90% of bytes_limit. Per-dispatch MFU "
        "gauges are driven by the hot paths regardless — the knob only "
        "controls the sampler."),
    "MXTPU_DEVSTATS_POLL_S": (
        float, 1.0,
        "Device-memory sampler poll interval in seconds "
        "(telemetry/devstats.py)."),
    "MXTPU_DEVSTATS_EVAL_SYNC": (
        bool, False,
        "Block-until-ready inside STANDALONE EvalStep dispatches so the "
        "eval mxtpu_device_mfu observation measures exact device time. "
        "Off by default: a direct eval loop overlaps host prep with "
        "device execution and the sync would serialize it. Serving "
        "dispatches (under the batcher's devstats dispatch context) "
        "always observe — there the next step is a host materialization "
        "anyway (docs/OBSERVABILITY.md 'Device truth')."),
    "MXTPU_DEVSTATS_TRAIN_SYNC": (
        bool, False,
        "Block-until-ready inside the TrainStep dispatch window so the "
        "train mxtpu_device_mfu observation measures exact device time. "
        "Off by default: the sync defeats donated-buffer step chaining "
        "(steps serialize on the host), so unsynced train MFU can read "
        "HIGH when steps pipeline — turn on when attributing a training "
        "regression, off for peak throughput (docs/OBSERVABILITY.md)."),
    "MXTPU_PROFILE_DIR": (
        str, None,
        "Directory for on-demand jax.profiler captures "
        "(GET /debug/profile?seconds=N, devstats.capture_profile). "
        "Unset: <tmpdir>/mxtpu_profile. Bounded: only the newest "
        "MXTPU_PROFILE_KEEP captures are kept."),
    "MXTPU_PROFILE_KEEP": (
        int, 4,
        "How many on-demand profiler captures survive in "
        "MXTPU_PROFILE_DIR (oldest pruned after each capture)."),
    "MXTPU_PROFILE_MAX_S": (
        float, 60.0,
        "Upper clamp on GET /debug/profile?seconds=N capture length — an "
        "operator typo must not leave the profiler tracing for an hour."),
    "MXTPU_PROFILE_PYTHON_TRACER": (
        bool, False,
        "Include python frames in profiler captures. OFF by default: the "
        "python tracer taxes every interpreter call while tracing (~30% "
        "on a timer-bound serving request), which lands on p99 whenever "
        "a capture overlaps traffic — the continuous profstats daemon's "
        "whole operating mode. The XLA op events the attribution layer "
        "reads survive with it off."),
    "MXTPU_PROFSTATS": (
        bool, False,
        "Autostart the continuous low-duty-cycle profiler daemon at "
        "package import (telemetry/profstats.py; profstats.start()/"
        "stop() at runtime): every MXTPU_PROFSTATS_INTERVAL_S it "
        "captures MXTPU_PROFSTATS_CAPTURE_S of jax.profiler trace and "
        "folds the per-op summary into "
        "mxtpu_profile_op_seconds_total{model,category} / "
        "mxtpu_profile_device_idle_ratio and GET /debug/hotspots "
        "(docs/OBSERVABILITY.md 'Op-level attribution')."),
    "MXTPU_PROFSTATS_INTERVAL_S": (
        float, 300.0,
        "Seconds between continuous-profiler capture cycles "
        "(telemetry/profstats.py daemon)."),
    "MXTPU_PROFSTATS_CAPTURE_S": (
        float, 2.0,
        "Trace length per continuous-profiler cycle; clamped to "
        "MXTPU_PROFSTATS_MAX_DUTY x MXTPU_PROFSTATS_INTERVAL_S so the "
        "profiler stays a sampling tax, never steady tracing."),
    "MXTPU_PROFSTATS_MAX_LOAD": (
        float, 0.5,
        "Queue-occupancy ceiling above which a continuous-profiler "
        "cycle is skipped (outcome=skipped_load on "
        "mxtpu_profile_captures_total): profiling is for finding the "
        "MFU gap, not for widening it under overload. Load probes: "
        "each serving ModelRegistry registers its max replica-queue "
        "occupancy (profstats.add_load_probe)."),
    "MXTPU_PROFSTATS_MAX_DUTY": (
        float, 0.02,
        "Overhead budget: max fraction of each daemon interval spent "
        "tracing (the capture length clamp)."),
    "MXTPU_PROFSTATS_SUMMARIES": (
        int, 32,
        "How many capture summaries the bounded profstats store keeps "
        "for GET /debug/hotspots?capture=<id> re-fetch — summaries "
        "outlive the pruned capture dirs themselves "
        "(MXTPU_PROFILE_KEEP)."),
    "MXTPU_HISTORY": (
        bool, False,
        "Autostart the metric-history daemon at package import "
        "(telemetry/history.py; history.start()/stop() at runtime): "
        "every MXTPU_HISTORY_INTERVAL_S it self-scrapes the telemetry "
        "registry into bounded per-series rings, evaluates the "
        "recording rules (rate(), queue-depth slope, window MFU, "
        "burn-rate trajectory) and the pressure_rising/mfu_droop early "
        "warnings, and serves GET /debug/history and /debug/incident "
        "(docs/OBSERVABILITY.md 'Metric history & incident timelines')."),
    "MXTPU_HISTORY_INTERVAL_S": (
        float, 10.0,
        "Seconds between metric-history self-scrape ticks. Retention is "
        "a direct function of it: MXTPU_HISTORY_RAW ticks of raw points "
        "plus MXTPU_HISTORY_COARSE x MXTPU_HISTORY_COARSE_EVERY ticks "
        "of min/max/mean summaries."),
    "MXTPU_HISTORY_RAW": (
        int, 512,
        "Raw ring length per history series: the newest N (t, value) "
        "points kept at full scrape resolution (telemetry/history.py). "
        "At the default 10s interval: ~85 minutes of raw history."),
    "MXTPU_HISTORY_COARSE": (
        int, 512,
        "Coarse ring length per history series: N downsampled "
        "{t, min, max, mean} points, each folding "
        "MXTPU_HISTORY_COARSE_EVERY raw samples — the long-horizon tier "
        "raw points age out into."),
    "MXTPU_HISTORY_COARSE_EVERY": (
        int, 8,
        "Raw samples folded into one coarse min/max/mean point. The "
        "fold keeps extremes honest: a one-tick queue spike survives "
        "into the coarse tier as that window's max, never averaged "
        "away."),
    "MXTPU_HISTORY_MAX_SERIES": (
        int, 1024,
        "Bound on distinct series the history store retains (scraped + "
        "derived recording-rule series). Past it, NEW series are "
        "dropped and counted on "
        "mxtpu_history_store_dropped_series_total; established series "
        "keep recording — history must never OOM the process it "
        "observes."),
    "MXTPU_HISTORY_FILE": (
        str, None,
        "When set, every history tick also exports the full store to "
        "this path as canonical JSONL (atomic tmp+rename rotation) — "
        "the offline artifact tools/tsq.py queries, diffs, and "
        "sparkline-renders."),
    "MXTPU_HISTORY_SLOPE_WINDOW_S": (
        float, 60.0,
        "Trailing window for the least-squares slope recording rules "
        "(queue depth, SLO burn rate) — the trend the pressure_rising "
        "predictor extrapolates."),
    "MXTPU_HISTORY_PRESSURE_HORIZON_S": (
        float, 60.0,
        "pressure_rising fires when a model's queue-depth trend line "
        "predicts crossing its capacity within this many seconds; the "
        "open episode only closes when the prediction retreats past "
        "twice the horizon (hysteresis) or the slope turns "
        "non-positive."),
    "MXTPU_HISTORY_PRESSURE_DEPTH": (
        float, None,
        "Fallback saturation depth for pressure_rising when a model "
        "exports no mxtpu_serving_queue_capacity gauge (the serving "
        "batcher exports queue_size x replicas automatically). None: "
        "no capacity, no prediction."),
    "MXTPU_HISTORY_DROOP_FRAC": (
        float, 0.7,
        "mfu_droop fires when the window MFU falls below this fraction "
        "of its trailing MXTPU_HISTORY_DROOP_WINDOW_S median; the "
        "episode re-arms only after MFU recovers halfway back to the "
        "median (hysteresis)."),
    "MXTPU_HISTORY_DROOP_WINDOW_S": (
        float, 600.0,
        "Trailing window whose median window-MFU is the mfu_droop "
        "baseline (the '10-minute median' the early warning compares "
        "against)."),
    "MXTPU_LOADGEN_SEED": (
        int, 0,
        "Arrival-process RNG seed for the open-loop load generator "
        "(tools/loadgen.py): Poisson inter-arrival draws are fully "
        "deterministic given it, so two soaks offer byte-identical "
        "schedules. Read stdlib-side by the tool (it must drive a remote "
        "server without the framework importable); registered here for "
        "docs and env hygiene (docs/LOADGEN.md)."),
    "MXTPU_LOADGEN_TIMEOUT_S": (
        float, 30.0,
        "Per-request HTTP timeout for the load generator's clients; a "
        "request past it records a transport error (status 599), never "
        "a hang. Read stdlib-side by tools/loadgen.py."),
    "MXTPU_LOADGEN_MAX_CLIENTS": (
        int, 256,
        "Bound on the load generator's concurrent in-flight requests. "
        "Arrivals past the bound are recorded as client-dropped (the "
        "offered-load accounting stays exact) instead of silently "
        "unsent or queued client-side — client-side queueing would "
        "re-introduce the coordinated-omission bias the open-loop "
        "design exists to avoid. Read stdlib-side by tools/loadgen.py."),
    "MXTPU_PERFGATE_REPEATS": (
        int, 3,
        "Default repeat count for tools/perfgate.py --cmd runs: repeats "
        "interleave in time and the gate aggregates per-metric minima "
        "(maxima for higher-is-better), so co-tenant noise — which only "
        "ever inflates a latency or deflates a throughput — is absorbed "
        "instead of widening tolerance bands (docs/LOADGEN.md)."),
    "MXTPU_PERFGATE_TOLERANCE": (
        float, 0.5,
        "Default relative tolerance band for perfgate metrics whose "
        "PERF_BASELINE.json entry doesn't pin its own: lower-is-better "
        "fails past baseline*(1+tol), higher-is-better below "
        "baseline*(1-tol). Read stdlib-side by tools/perfgate.py."),
    "MXTPU_SLO_TARGET": (
        float, 0.99,
        "Default availability objective for the per-model SLOs the serving "
        "registry seeds at load (telemetry/slo.py): the fraction of "
        "eligible requests (2xx good; 429/504/5xx bad; other 4xx not "
        "counted) that must succeed. The error budget is 1 - target — "
        "burn rates are bad-fraction / (1 - target) "
        "(docs/OBSERVABILITY.md 'SLOs and tenants')."),
    "MXTPU_SLO_LATENCY_MS": (
        float, None,
        "When set, every served model also gets a latency SLO: a 2xx "
        "response slower than this many milliseconds end-to-end (the "
        "http:predict span window) counts against the latency error "
        "budget. None = availability SLO only (telemetry/slo.py)."),
    "MXTPU_SLO_WINDOW_S": (
        float, 3600.0,
        "Error-budget accounting window in seconds for "
        "mxtpu_slo_budget_remaining: the sliding window over which spent "
        "budget is computed (and refills as bad events age out). The SRE "
        "30-day convention is impractical for a process-local ledger; one "
        "hour is the operational default (telemetry/slo.py)."),
    "MXTPU_SLO_WINDOWS": (
        str, "300:3600,3600:21600",
        "Multi-window burn-rate alert pairs as SHORT:LONG second pairs, "
        "comma-separated, fastest first (default: the SRE-workbook 5m/1h "
        "fast pair and 1h/6h slow pair). An alert pair breaches only when "
        "BOTH its windows' burn rates exceed the pair's threshold — the "
        "short window gives detection speed, the long one suppresses "
        "blips. CI scales these down to seconds (telemetry/slo.py)."),
    "MXTPU_SLO_FAST_BURN": (
        float, 14.4,
        "Burn-rate threshold for the FIRST (fast) alert-window pair: 14.4 "
        "means the error budget is being spent 14.4x faster than the "
        "objective allows (the SRE-workbook page-now threshold — 2% of a "
        "30-day budget in one hour)."),
    "MXTPU_SLO_SLOW_BURN": (
        float, 6.0,
        "Burn-rate threshold for the second and later (slow) alert-window "
        "pairs (the SRE-workbook ticket threshold — 5% of a 30-day "
        "budget in six hours)."),
    "MXTPU_ACCESSLOG_SIZE": (
        int, 4096,
        "Bound on the structured per-request access-log ring "
        "(serving/accesslog.py): one record per terminal predict outcome "
        "{ts, request_id, tenant, model, code, shed_reason, queue_ms, "
        "batch_ms, device_ms, replica, bucket}, oldest aged out. Served "
        "at GET /debug/requests?n=."),
    "MXTPU_ACCESSLOG_FILE": (
        str, None,
        "When set, access-log records are ALSO appended to this path as "
        "JSONL (sampled by MXTPU_ACCESSLOG_SAMPLE). None disables file "
        "export; the in-memory ring and /debug/requests stay on "
        "regardless (serving/accesslog.py)."),
    "MXTPU_ACCESSLOG_SAMPLE": (
        float, 1.0,
        "Deterministic sampling rate (0..1) for the access-log JSONL file "
        "export: a stride sampler writes every record at 1.0, every "
        "second record at 0.5, none at 0 — deterministic, not random, so "
        "two identical runs export identical files "
        "(serving/accesslog.py)."),
    "MXTPU_NUMWATCH_SAMPLE": (
        float, 0.0,
        "Numerics-sentinel tap sampling rate (telemetry/numwatch.py): 0 "
        "disables the on-device stats taps (the default); a rate r in "
        "(0, 1] taps every round(1/r)-th dispatch at each site "
        "(deterministic stride, not random — two identical runs tap "
        "identical dispatches). Tap sites: TrainStep loss/params, "
        "serving dispatch outputs, decode-loop logits "
        "(docs/OBSERVABILITY.md 'Numerical health')."),
    "MXTPU_SHADOW_SAMPLE": (
        float, 0.0,
        "Default shadow-execution sampling rate for models with a "
        "registered reference servable (numwatch.register_shadow): 0 "
        "disables; rate r re-executes every round(1/r)-th dispatched "
        "batch through the reference on a background worker and compares "
        "outputs into mxtpu_shadow_divergence{model,metric}. A per-model "
        "stride passed to register_shadow overrides this."),
    "MXTPU_SHADOW_THRESHOLD": (
        float, 0.25,
        "Max-abs-diff breach threshold for shadow divergence: a shadow "
        "sample whose primary-vs-reference max absolute output "
        "difference exceeds this flips the served model's health to "
        "degraded (once per breach episode) and fires a shadow_breach "
        "flightrec event (telemetry/numwatch.py)."),
    "MXTPU_SEED": (
        int, None,
        "Global RNG seed applied at package import (MXNET_SEED analog): "
        "seeds nd.random, np.random and the functional key stream."),
    "MXTPU_CONV_BWD_PALLAS": (
        bool, True,
        "Gate for the fused Pallas conv-backward kernel (dgrad+wgrad in "
        "one HBM pass): ops.conv_bwd.conv3x3_s1 routes its backward "
        "through it when the shape is legal on TPU. Model-zoo convs keep "
        "XLA's lowering (see docs/PERF_RESNET.md pilot disposition)."),
    "MXTPU_CPU_WORKER_NTHREADS": (
        int, 4,
        "Default decode/augment thread count for the native "
        "ImageRecordIter when preprocess_threads is not given "
        "(MXNET_CPU_WORKER_NTHREADS analog)."),
    "MXTPU_TEST_LARGE_TENSOR": (
        bool, False,
        "Opt into the >2^31-element int64 large-tensor test tier "
        "(tests/test_large_tensor.py; ~2-6 GB of host RAM)."),
    "JAX_PLATFORMS": (
        str, None,
        "Backend selection (jax): 'cpu' forces the virtual-device CPU path "
        "used by tests and DataLoader process workers."),
    "XLA_FLAGS": (
        str, None,
        "XLA compiler flags; tests use "
        "--xla_force_host_platform_device_count=8 for the virtual mesh."),
}


def get_env(name):
    """Typed read of a registered variable (raises on unknown names)."""
    if name not in ENV_VARS:
        raise KeyError("unregistered env var %r — add it to config.ENV_VARS"
                       % name)
    typ, default, _doc = ENV_VARS[name]
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is bool:
        return raw.strip().lower() not in ("0", "", "false", "no", "off")
    return typ(raw)


def evict_to_bound(cache, on_evict=None):
    """Drop least-recently-USED entries of an executable cache until it
    fits MXTPU_EXEC_CACHE_SIZE (call after inserting).

    LRU contract: python dicts iterate in insertion order, so a caller
    marking a hit must move the entry to the end (``cache[k] =
    cache.pop(k)``) — then insertion order IS recency order and the front
    entry is the least-recently-dispatched one. Pure insert-only callers
    degrade to the old FIFO behavior. ``on_evict(key, value)`` runs per
    victim (metrics hooks); the shared AOT cache (aot.AOTCache) has its
    own timestamped LRU + mxtpu_aot_evictions_total counter and does not
    route through here.
    """
    bound = max(1, get_env("MXTPU_EXEC_CACHE_SIZE"))
    while len(cache) > bound:
        key = next(iter(cache))
        value = cache.pop(key)
        if on_evict is not None:
            on_evict(key, value)


def describe():
    """Render the registry as the env_var.md-style table."""
    lines = ["%-24s %-6s %-10s %s" % ("Variable", "Type", "Default", "Doc")]
    for name, (typ, default, doc) in sorted(ENV_VARS.items()):
        lines.append("%-24s %-6s %-10s %s"
                     % (name, typ.__name__, str(default), doc))
    return "\n".join(lines)
