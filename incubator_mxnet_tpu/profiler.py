"""Profiler (ref src/profiler/profiler.h:251, python/mxnet/profiler.py).

Reference parity: set_config / set_state('run'/'stop') / dumps, scoped
``profiler.scope``, chrome://tracing JSON output, in-memory aggregate table.
TPU-native: wraps jax.profiler (XLA xplane traces for device time) and a
host-side event recorder emitting the same chrome-trace JSON format.
"""
from __future__ import annotations

import json
import threading
import time

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause", "resume",
           "scope", "Marker", "record_event", "record_batch", "device_memory",
           "memory_summary", "set_memory_source", "now_us"]

# Event timing: time.time() is NOT monotonic — an NTP clock step mid-run
# makes durations negative and reorders trace events. All event timestamps
# derive from time.perf_counter() (monotonic) anchored ONCE to the wall
# clock at import, so traces still carry real epoch microseconds but
# differences are always perf_counter differences.
_EPOCH_TIME_S = time.time()
_EPOCH_PERF_S = time.perf_counter()


def now_us():
    """Epoch-anchored monotonic timestamp in microseconds — the one clock
    every profiler event (and serving's record_batch hook) uses."""
    return (_EPOCH_TIME_S + (time.perf_counter() - _EPOCH_PERF_S)) * 1e6

_CONFIG = {"filename": "profile.json", "aggregate_stats": True,
           # profile_imperative: instrument EVERY eager op at the _apply
           # choke point (ref per-op engine profiling, profiler.h:251).
           # Each op is synced to time real device work — turn off to
           # profile async pipelining instead.
           "profile_imperative": True,
           # profile_memory: sample PJRT device memory after each profiled
           # op (≙ storage_profiler.h GpuDeviceStorageProfiler) — emits
           # chrome-trace counter events and a Mem column in the aggregate
           "profile_memory": True}
_STATE = {"running": False, "jax_trace_dir": None, "peak_bytes": 0}
_EVENTS = []
_LOCK = threading.Lock()
_AGG = {}
_MEM_SOURCE = None  # injectable for tests / non-PJRT backends


def set_config(**kwargs):
    """ref profiler.py set_config (filename, profile_all, aggregate_stats...)."""
    _CONFIG.update(kwargs)


def set_state(state_="stop", profile_process="worker"):
    """ref profiler.py set_state('run'|'stop')."""
    if state_ == "run" and not _STATE["running"]:
        _STATE["running"] = True
        _STATE["peak_bytes"] = 0  # fresh session, fresh peak
        try:
            import jax
            trace_dir = _CONFIG.get("jax_trace_dir")
            if trace_dir:
                jax.profiler.start_trace(trace_dir)
                _STATE["jax_trace_dir"] = trace_dir
        except Exception:
            pass
    elif state_ == "stop" and _STATE["running"]:
        _STATE["running"] = False
        if _STATE["jax_trace_dir"]:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            _STATE["jax_trace_dir"] = None


def state():
    return "run" if _STATE["running"] else "stop"


def record_event(name, categories="host", start_us=None, dur_us=None,
                 args=None):
    """Record one host-side event (complete-event 'X' phase).

    The per-event trace list is bounded (config max_events, default 500k;
    oldest-first semantics: recording stops at the cap, aggregation
    continues) so long profiled runs do not grow memory without bound."""
    if not _STATE["running"]:
        return
    with _LOCK:
        if len(_EVENTS) < _CONFIG.get("max_events", 500_000):
            ev = {"name": name, "cat": categories, "ph": "X",
                  "ts": start_us if start_us is not None else now_us(),
                  "dur": dur_us or 0, "pid": 0, "tid": threading.get_ident()}
            if args is not None:
                ev["args"] = args
            _EVENTS.append(ev)
        agg = _AGG.setdefault(name, {"count": 0, "total_us": 0.0, "max_us": 0.0})
        agg["count"] += 1
        agg["total_us"] += dur_us or 0
        agg["max_us"] = max(agg["max_us"], dur_us or 0)


def record_batch(model, size, bucket, start_us=None, dur_us=None,
                 request_ids=None):
    """Per-dispatch serving hook (serving/batcher.py): one complete event
    per dispatched batch, named by model and padded bucket shape so the
    aggregate table groups rows per compiled executable; the real
    (non-padding) item count rides along as an event arg, and
    ``request_ids`` — the trace ids of the coalesced requests — make one
    slow HTTP request followable queue -> bucket -> device in the dump."""
    args = {"batch_size": size, "bucket": bucket}
    if request_ids:
        args["request_ids"] = list(request_ids)
    record_event("serve:%s:batch%d" % (model, bucket), "serving",
                 start_us, dur_us, args=args)


class Marker:
    """Scoped host event (≙ ProfileTask/ProfileEvent)."""

    def __init__(self, name, categories="host"):
        self.name = name
        self.categories = categories

    def __enter__(self):
        self._t0 = now_us()
        return self

    def __exit__(self, *a):
        record_event(self.name, self.categories, self._t0,
                     now_us() - self._t0)


class scope:
    """ref profiler.py profiler.scope — names nested under a prefix."""

    _current = threading.local()

    def __init__(self, name="<unk>:"):
        self.name = name

    def __enter__(self):
        self._old = getattr(scope._current, "value", "")
        scope._current.value = self._old + self.name
        return self

    def __exit__(self, *a):
        scope._current.value = self._old


def imperative_active():
    """Fast check used by ndarray._apply (the eager dispatch choke point)."""
    return _STATE["running"] and _CONFIG.get("profile_imperative", True)


def record_op(name, t0_us, outs):
    """Record one eager op: syncs outputs so duration covers device work.
    Ops inside a jit trace (compiled-step build) are skipped — they are not
    executions, and the device profile covers the compiled program."""
    import jax
    if any(isinstance(o, jax.core.Tracer) for o in outs):
        return
    try:
        jax.block_until_ready([o for o in outs])
    except Exception:
        pass
    prefix = getattr(scope._current, "value", "")
    full = "op:" + prefix + name
    record_event(full, "operator", t0_us, now_us() - t0_us)
    if _CONFIG.get("profile_memory", True):
        _sample_memory(full)


def set_memory_source(fn):
    """Override where memory samples come from (fn() -> bytes_in_use int,
    or -> {'bytes_in_use': int, 'peak_bytes_in_use': int}). Used by tests
    and by backends whose PJRT client reports no memory_stats (CPU)."""
    global _MEM_SOURCE
    _MEM_SOURCE = fn


def _mem_now():
    """(bytes_in_use, peak_bytes_in_use) summed over local devices, or None."""
    if _MEM_SOURCE is not None:
        s = _MEM_SOURCE()
        if isinstance(s, dict):
            return (int(s.get("bytes_in_use", 0)),
                    int(s.get("peak_bytes_in_use",
                              s.get("bytes_in_use", 0))))
        return int(s), int(s)
    import jax
    live = peak = 0
    seen = False
    for d in jax.local_devices():
        try:
            s = d.memory_stats() or {}
        except Exception:
            s = {}
        if "bytes_in_use" in s:
            seen = True
            live += s["bytes_in_use"]
            peak += s.get("peak_bytes_in_use", s["bytes_in_use"])
    return (live, peak) if seen else None


def _sample_memory(op_name):
    """Attach a live-memory sample to the op's aggregate row and emit a
    chrome-trace counter event (the storage-profiler view)."""
    mem = _mem_now()
    if mem is None:
        return
    live, peak = mem
    with _LOCK:
        _STATE["peak_bytes"] = max(_STATE["peak_bytes"], peak, live)
        agg = _AGG.get(op_name)
        if agg is not None:
            agg["mem_bytes"] = live
            agg["peak_mem_bytes"] = max(agg.get("peak_mem_bytes", 0), live)
        if len(_EVENTS) < _CONFIG.get("max_events", 500_000):
            _EVENTS.append({"name": "device_memory", "ph": "C",
                            "ts": now_us(), "pid": 0,
                            "args": {"bytes_in_use": live}})


def device_memory():
    """Per-device memory stats (≙ the reference's storage profiler,
    src/profiler/storage_profiler.h), delegated to the devstats sampler
    snapshot (telemetry/devstats.py). Stable keys: ``bytes_in_use`` /
    ``peak_bytes_in_use`` / ``bytes_limit`` per device; backends whose
    PJRT client reports no memory stats (CPU) degrade to host-RSS
    report-only samples under ``'host'`` (``rss_bytes`` /
    ``peak_rss_bytes``) instead of empty dicts. When the sampler daemon
    runs, this returns its last snapshot without touching the device —
    so a host-only tool dumping a trace gets the newest known numbers
    even without a live jax sample path ({} only if devstats itself is
    unimportable)."""
    try:
        from .telemetry import devstats
        return devstats.device_memory()
    except Exception:
        return {}


def memory_summary():
    """Formatted per-device memory table + the profiled-run peak (the
    reference's storage-profiler dump). Renders the devstats host-RSS
    report-only fallback row (rss_bytes/peak_rss_bytes under 'host')
    when the backend exposes no PJRT memory stats — zeros there would
    defeat the fallback's whole point."""
    lines = ["%-24s %14s %14s %14s"
             % ("Device", "Live(MB)", "Peak(MB)", "Limit(MB)")]
    mb = 1.0 / (1024 * 1024)
    for dev, s in device_memory().items():
        lines.append("%-24s %14.1f %14.1f %14.1f"
                     % (dev,
                        s.get("bytes_in_use", s.get("rss_bytes", 0)) * mb,
                        s.get("peak_bytes_in_use",
                              s.get("peak_rss_bytes", 0)) * mb,
                        s.get("bytes_limit", 0) * mb))
    lines.append("profiled-run peak: %.1f MB"
                 % (_STATE["peak_bytes"] * mb))
    return "\n".join(lines)


def pause(profile_process="worker"):
    _STATE["running"] = False


def resume(profile_process="worker"):
    _STATE["running"] = True


def dumps(reset=False, format="table"):
    """Aggregate stats table (ref aggregate_stats.cc), busiest first.
    The Mem column is the device bytes_in_use sampled after the op's most
    recent execution (storage-profiler view; '-' when the backend reports
    no memory stats and no source was injected)."""
    lines = ["%-48s %8s %12s %10s %10s %10s"
             % ("Name", "Calls", "Total(us)", "Avg(us)", "Max(us)",
                "Mem(MB)")]
    mb = 1.0 / (1024 * 1024)
    with _LOCK:
        order = sorted(_AGG.items(), key=lambda kv: -kv[1]["total_us"])
        for name, agg in order:
            mem = ("%10.1f" % (agg["mem_bytes"] * mb)) \
                if "mem_bytes" in agg else "%10s" % "-"
            lines.append("%-48s %8d %12.1f %10.1f %10.1f %s"
                         % (name[:48], agg["count"], agg["total_us"],
                            agg["total_us"] / max(agg["count"], 1),
                            agg["max_us"], mem))
        if reset:
            _AGG.clear()
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (ref profiler.h EmitEvents). Includes
    device_memory counter events recorded per op and a final per-device
    snapshot under 'deviceMemory' (storage_profiler.h analog).

    _LOCK is held only to snapshot the event list: device_memory() is a
    device sync (plus a jax import) and the file write is arbitrary I/O —
    holding the lock across either would block every hot-path
    record_event() for the dump's duration. Events recorded while the
    file is being written survive into the next dump (only the
    snapshotted prefix is cleared)."""
    with _LOCK:
        events = list(_EVENTS)
        peak = _STATE["peak_bytes"]
    payload = {"traceEvents": events, "displayTimeUnit": "ms",
               "deviceMemory": device_memory(),
               "profiledPeakBytes": peak}
    with open(_CONFIG["filename"], "w") as f:
        json.dump(payload, f)
    if finished:
        with _LOCK:
            # drop exactly what was dumped; concurrent appends stay
            del _EVENTS[:len(events)]
