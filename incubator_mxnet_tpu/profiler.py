"""Profiler (ref src/profiler/profiler.h:251, python/mxnet/profiler.py).

Reference parity: set_config / set_state('run'/'stop') / dumps, scoped
``profiler.scope``, chrome://tracing JSON output, in-memory aggregate table.
TPU-native: wraps jax.profiler (XLA xplane traces for device time) and a
host-side event recorder emitting the same chrome-trace JSON format.
"""
from __future__ import annotations

import json
import threading
import time

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause", "resume",
           "scope", "Marker", "record_event"]

_CONFIG = {"filename": "profile.json", "aggregate_stats": True}
_STATE = {"running": False, "jax_trace_dir": None}
_EVENTS = []
_LOCK = threading.Lock()
_AGG = {}


def set_config(**kwargs):
    """ref profiler.py set_config (filename, profile_all, aggregate_stats...)."""
    _CONFIG.update(kwargs)


def set_state(state_="stop", profile_process="worker"):
    """ref profiler.py set_state('run'|'stop')."""
    if state_ == "run" and not _STATE["running"]:
        _STATE["running"] = True
        try:
            import jax
            trace_dir = _CONFIG.get("jax_trace_dir")
            if trace_dir:
                jax.profiler.start_trace(trace_dir)
                _STATE["jax_trace_dir"] = trace_dir
        except Exception:
            pass
    elif state_ == "stop" and _STATE["running"]:
        _STATE["running"] = False
        if _STATE["jax_trace_dir"]:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            _STATE["jax_trace_dir"] = None


def state():
    return "run" if _STATE["running"] else "stop"


def record_event(name, categories="host", start_us=None, dur_us=None):
    """Record one host-side event (complete-event 'X' phase)."""
    if not _STATE["running"]:
        return
    with _LOCK:
        _EVENTS.append({"name": name, "cat": categories, "ph": "X",
                        "ts": start_us if start_us is not None else time.time() * 1e6,
                        "dur": dur_us or 0, "pid": 0, "tid": threading.get_ident()})
        agg = _AGG.setdefault(name, {"count": 0, "total_us": 0.0, "max_us": 0.0})
        agg["count"] += 1
        agg["total_us"] += dur_us or 0
        agg["max_us"] = max(agg["max_us"], dur_us or 0)


class Marker:
    """Scoped host event (≙ ProfileTask/ProfileEvent)."""

    def __init__(self, name, categories="host"):
        self.name = name
        self.categories = categories

    def __enter__(self):
        self._t0 = time.time() * 1e6
        return self

    def __exit__(self, *a):
        record_event(self.name, self.categories, self._t0,
                     time.time() * 1e6 - self._t0)


class scope:
    """ref profiler.py profiler.scope — names nested under a prefix."""

    _current = threading.local()

    def __init__(self, name="<unk>:"):
        self.name = name

    def __enter__(self):
        self._old = getattr(scope._current, "value", "")
        scope._current.value = self._old + self.name
        return self

    def __exit__(self, *a):
        scope._current.value = self._old


def pause(profile_process="worker"):
    _STATE["running"] = False


def resume(profile_process="worker"):
    _STATE["running"] = True


def dumps(reset=False, format="table"):
    """Aggregate stats table (ref aggregate_stats.cc)."""
    lines = ["%-40s %8s %12s %12s" % ("Name", "Calls", "Total(us)", "Max(us)")]
    with _LOCK:
        for name, agg in sorted(_AGG.items()):
            lines.append("%-40s %8d %12.1f %12.1f"
                         % (name[:40], agg["count"], agg["total_us"], agg["max_us"]))
        if reset:
            _AGG.clear()
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write chrome://tracing JSON (ref profiler.h EmitEvents)."""
    with _LOCK:
        payload = {"traceEvents": list(_EVENTS), "displayTimeUnit": "ms"}
        with open(_CONFIG["filename"], "w") as f:
            json.dump(payload, f)
        if finished:
            _EVENTS.clear()
