"""Subgraph partitioning API — the custom-accelerator-backend hook
(ref src/operator/subgraph/subgraph_property.h:252 SubgraphProperty,
python/mxnet symbol.optimize_for).

TPU-native role: XLA already owns fusion for the compiled path, so the
default execution needs no partitioner. This API exists for what the
reference used it for — plugging a BACKEND in: grouping matched operators
into subgraph nodes a backend can claim (int8 paths, custom accelerators,
vendor libraries). A partitioned Symbol stays a Symbol: subgraph nodes
evaluate their captured sub-DAG through the same op implementations, so
bind/eval/gradients keep working.

Usage::

    class MyBackend(SubgraphProperty):
        def match(self, node):             # op whitelist
            return node.op_name in ("dot", "add", "relu")
    register_backend("my_backend", MyBackend)
    part = sym.optimize_for("my_backend")  # or subgraph.partition(sym, ...)
"""
from __future__ import annotations

__all__ = ["SubgraphProperty", "register_backend", "get_backend", "partition"]

_BACKENDS = {}


class SubgraphProperty:
    """Backend description: which nodes it claims, and how to wrap them
    (ref subgraph_property.h SubgraphProperty / SubgraphSelector)."""

    name = "base"

    def match(self, node):
        """Whether this backend claims ``node`` (a non-variable Symbol)."""
        raise NotImplementedError

    def pre_partition(self, sym):
        return sym

    def post_partition(self, sym):
        return sym

    def create_subgraph_op(self, fn, nodes):
        """Hook: wrap the fused callable (e.g. quantize/compile it)."""
        return fn


def register_backend(name, prop_cls):
    """ref MXNET_REGISTER_SUBGRAPH_BACKEND / subgraph_property.h:429."""
    _BACKENDS[name] = prop_cls
    return prop_cls


def get_backend(name):
    if name not in _BACKENDS:
        raise ValueError("subgraph backend %r not registered (have: %s)"
                         % (name, sorted(_BACKENDS)))
    return _BACKENDS[name]()


def _topo(sym):
    seen, order = set(), []

    def visit(s):
        base = getattr(s, "_base", None) or s
        if id(base) in seen:
            return
        seen.add(id(base))
        for i in base._inputs:
            visit(i)
        order.append(base)

    visit(sym)
    return order


def partition(sym, backend):
    """Group matched connected operators into subgraph nodes.

    v1 contract (conservative, like the reference's default selector):
    only single-output components are fused — a matched component whose
    intermediate values are consumed outside stays unfused. Multi-output
    heads are left to the backend's own selector subclassing.
    """
    from .symbol.symbol import Symbol

    prop = backend if isinstance(backend, SubgraphProperty) else \
        get_backend(backend)
    sym = prop.pre_partition(sym)
    nodes = _topo(sym)
    matched = {id(n) for n in nodes
               if not n.is_var and n._num_outputs == 1 and prop.match(n)}

    # consumers map over the whole graph
    consumers = {}
    for n in nodes:
        for i in n._inputs:
            b = getattr(i, "_base", None) or i
            consumers.setdefault(id(b), []).append(n)

    # connected components among matched nodes (union-find over input edges)
    parent = {i: i for i in matched}

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[ra] = rb

    byid = {id(n): n for n in nodes}
    for n in nodes:
        if id(n) not in matched:
            continue
        for i in n._inputs:
            b = getattr(i, "_base", None) or i
            if id(b) in matched:
                union(id(n), id(b))

    groups = {}
    for i in matched:
        groups.setdefault(find(i), []).append(byid[i])

    # keep only components with exactly ONE node consumed outside (the root)
    fuse = {}  # id(root) -> list of member nodes
    for comp in groups.values():
        if len(comp) < 2:
            continue
        ids = {id(n) for n in comp}
        ext_out = [n for n in comp
                   if any(id(c) not in ids for c in consumers.get(id(n), []))
                   or not consumers.get(id(n))]
        if len(ext_out) == 1:
            fuse[id(ext_out[0])] = comp

    if not fuse:
        return prop.post_partition(sym)

    # rebuild the DAG bottom-up, replacing each fused component's root
    rebuilt = {}

    def rebuild(s):
        base = getattr(s, "_base", None) or s
        if id(base) in rebuilt:
            new = rebuilt[id(base)]
        elif base.is_var:
            new = base
        elif id(base) in fuse:
            comp_ids = {id(n) for n in fuse[id(base)]}
            # external inputs of the component, in first-use order; keyed by
            # (node, output_index) so two outputs of one multi-output node
            # stay distinct
            ext, seen_ext = [], set()
            for n in fuse[id(base)]:
                for i in n._inputs:
                    ib = getattr(i, "_base", None) or i
                    k = (id(ib), i._output_index)
                    if id(ib) not in comp_ids and k not in seen_ext:
                        seen_ext.add(k)
                        ext.append(i)
            root = base

            def fused_fn(*ext_vals, _root=root, _ext=tuple(ext)):
                cache = {}
                for e, v in zip(_ext, ext_vals):
                    eb = getattr(e, "_base", None) or e
                    cache[(id(eb), e._output_index)] = v
                    cache[(id(eb), None)] = v

                def ev(s2):
                    b2 = getattr(s2, "_base", None) or s2
                    k = (id(b2), s2._output_index)
                    if k in cache:
                        return cache[k]
                    args = [ev(i) for i in b2._inputs]
                    out = b2._op(*args, **b2._kwargs)
                    cache[k] = out
                    return out

                return ev(_root)

            fused_fn = prop.create_subgraph_op(fused_fn, fuse[id(base)])
            new = Symbol(op=fused_fn,
                         op_name="_subgraph_%s" % prop.name,
                         inputs=[rebuild(e) for e in ext],
                         name="%s_subgraph%d" % (prop.name, len(rebuilt)))
        else:
            new = Symbol(op=base._op, op_name=base._op_name,
                         inputs=[rebuild(i) for i in base._inputs],
                         kwargs=base._kwargs, name=base.name,
                         num_outputs=base._num_outputs)
            new._attr = dict(base._attr)
        rebuilt[id(base)] = new
        if s._output_index is not None:
            return new[s._output_index]
        return new

    out = rebuild(sym)
    return prop.post_partition(out)
