"""Evaluation metrics (ref python/mxnet/metric.py:67 EvalMetric + ~20 metrics)."""
from __future__ import annotations

import math

import numpy as onp

from .base import registry
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy", "F1", "MCC",
           "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "PearsonCorrelation", "Loss", "Torch", "Caffe", "CustomMetric", "np", "create"]

_REG = registry("metric")


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def check_label_shapes(labels, preds, wrap=False, shape=False):
    if not shape:
        lshape, pshape = len(labels), len(preds)
    else:
        lshape, pshape = labels.shape, preds.shape
    if lshape != pshape:
        raise ValueError("Shape of labels %s does not match shape of predictions %s"
                         % (lshape, pshape))
    if wrap:
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
    return labels, preds


class EvalMetric:
    """Base metric with global + per-batch accumulators (ref metric.py:67)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self.global_num_inst = 0
        self.global_sum_metric = 0.0

    def reset_local(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names if n in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names if n in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_global(self):
        if self.global_num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.global_sum_metric / self.global_num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def _add(self, metric, inst):
        self.sum_metric += metric
        self.num_inst += inst
        self.global_sum_metric += metric
        self.global_num_inst += inst

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kw):
        super().__init__(name, **kw)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()
        super().reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def register(klass):
    return _REG.register(klass)


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kw):
        super().__init__(name, axis=axis, **kw)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred), _as_np(label)
            if p.ndim > l.ndim:
                p = p.argmax(axis=self.axis)
            p = p.astype("int32").flatten()
            l = l.astype("int32").flatten()
            check_label_shapes(l, p, shape=True)
            self._add(float((p == l).sum()), len(p))


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kw):
        super().__init__(name + "_%d" % top_k, top_k=top_k, **kw)
        self.top_k = top_k

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred), _as_np(label).astype("int32")
            idx = onp.argpartition(p, -self.top_k, axis=-1)[..., -self.top_k:]
            hit = (idx == l[..., None]).any(axis=-1)
            self._add(float(hit.sum()), hit.size)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kw):
        super().__init__(name, **kw)
        self.average = average
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        self._tp = self._fp = self._fn = 0.0
        super().reset()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred), _as_np(label).astype("int32").flatten()
            if p.ndim > 1 and p.shape[-1] > 1:
                p = p.argmax(axis=-1)
            else:
                p = (p.flatten() > 0.5).astype("int32")
            p = p.astype("int32").flatten()
            self._tp += float(((p == 1) & (l == 1)).sum())
            self._fp += float(((p == 1) & (l == 0)).sum())
            self._fn += float(((p == 0) & (l == 1)).sum())
            prec = self._tp / (self._tp + self._fp) if self._tp + self._fp else 0.0
            rec = self._tp / (self._tp + self._fn) if self._tp + self._fn else 0.0
            f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
            self.sum_metric = f1
            self.num_inst = 1
            self.global_sum_metric = f1
            self.global_num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation coefficient."""

    def __init__(self, name="mcc", **kw):
        super().__init__(name, **kw)
        self._tp = self._fp = self._fn = self._tn = 0.0

    def reset(self):
        self._tp = self._fp = self._fn = self._tn = 0.0
        super().reset()

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred), _as_np(label).astype("int32").flatten()
            if p.ndim > 1 and p.shape[-1] > 1:
                p = p.argmax(axis=-1)
            else:
                p = (p.flatten() > 0.5)
            p = p.astype("int32").flatten()
            self._tp += float(((p == 1) & (l == 1)).sum())
            self._fp += float(((p == 1) & (l == 0)).sum())
            self._fn += float(((p == 0) & (l == 1)).sum())
            self._tn += float(((p == 0) & (l == 0)).sum())
            num = self._tp * self._tn - self._fp * self._fn
            den = math.sqrt((self._tp + self._fp) * (self._tp + self._fn) *
                            (self._tn + self._fp) * (self._tn + self._fn))
            mcc = num / den if den else 0.0
            self.sum_metric = mcc
            self.num_inst = 1
            self.global_sum_metric = mcc
            self.global_num_inst = 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kw):
        super().__init__(name, **kw)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred), _as_np(label).astype("int32")
            l = l.flatten()
            p = p.reshape(-1, p.shape[-1])
            probs = p[onp.arange(len(l)), l]
            if self.ignore_label is not None:
                ignore = (l == self.ignore_label)
                probs = onp.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss -= float(onp.log(onp.maximum(probs, 1e-10)).sum())
            num += len(l)
        self._add(loss, num)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred), _as_np(label)
            if l.ndim == 1:
                l = l.reshape(l.shape[0], 1) if p.ndim != 1 else l
            self._add(float(onp.abs(l - p).mean()), 1)


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred), _as_np(label)
            if l.ndim == 1 and p.ndim != 1:
                l = l.reshape(l.shape[0], 1)
            self._add(float(((l - p) ** 2).mean()), 1)


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kw):
        EvalMetric.__init__(self, name, **kw)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kw):
        super().__init__(name, **kw)
        self.eps = eps

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            l = _as_np(label).astype("int32").ravel()
            p = _as_np(pred)
            p = p.reshape(-1, p.shape[-1])
            prob = p[onp.arange(l.shape[0]), l]
            self._add(float((-onp.log(prob + self.eps)).sum()), l.shape[0])


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kw):
        EvalMetric.__init__(self, name, **kw)
        self.eps = eps


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kw):
        super().__init__(name, **kw)

    def update(self, labels, preds):
        labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            p, l = _as_np(pred).ravel(), _as_np(label).ravel()
            r = onp.corrcoef(p, l)[0, 1]
            self._add(float(r), 1)


@register
class Loss(EvalMetric):
    """Dummy metric reporting the mean of predictions (ref metric.py Loss)."""

    def __init__(self, name="loss", **kw):
        super().__init__(name, **kw)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            p = _as_np(pred)
            self._add(float(p.sum()), p.size)


@register
class Torch(Loss):
    def __init__(self, name="torch", **kw):
        EvalMetric.__init__(self, name, **kw)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", **kw):
        EvalMetric.__init__(self, name, **kw)


# MXNet-style aliases
_REG.register(Accuracy, "acc")
_REG.register(TopKAccuracy, "top_k_acc")
_REG.register(TopKAccuracy, "top_k_accuracy")
_REG.register(CrossEntropy, "ce")
_REG.register(NegativeLogLikelihood, "nll_loss")
_REG.register(PearsonCorrelation, "pearsonr")


class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False, **kw):
        name = name if name is not None else getattr(feval, "__name__", "custom")
        super().__init__("custom(%s)" % name, **kw)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            labels, preds = check_label_shapes(labels, preds, True)
        for label, pred in zip(labels, preds):
            l, p = _as_np(label), _as_np(pred)
            reval = self._feval(l, p)
            if isinstance(reval, tuple):
                m, n = reval
                self._add(m, n)
            else:
                self._add(reval, 1)


def np(numpy_feval, name=None, allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = getattr(numpy_feval, "__name__", "custom")
    return CustomMetric(feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m, *args, **kwargs))
        return composite
    return _REG.create(metric, *args, **kwargs)
