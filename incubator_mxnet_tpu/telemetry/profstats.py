"""Op-level profile intelligence: chrome-trace parsing, hotspot
attribution, and the continuous low-duty-cycle profiler daemon.

Devstats (telemetry/devstats.py) says a program is compute- or HBM-bound;
this module says *which op*. It is the layer between a raw
``jax.profiler`` capture directory (GET /debug/profile) and the ranked
hotspot list ROADMAP item 2's MFU sprint starts from:

- ``summarize_capture(dir)`` walks every ``*.trace.json[.gz]`` in a
  capture dir (stdlib gzip+json only) into per-op aggregates
  {op, XLA category, self-time, count, share} with proper self-time
  (nested umbrella events subtract their children), per-track device
  busy/idle, and the largest device-idle gaps.
- ``capture_and_summarize(seconds)`` wraps ``devstats.capture_profile``
  with before/after snapshots of the dispatch counters so the summary
  carries the devstats join: window MFU, per-category MFU contribution,
  per-op estimated FLOPs, and the host-side dispatch-bubble estimate
  (wall time inside ``serve:dispatch``/``train:step`` spans during the
  window minus device busy time).
- the daemon (``start()``/``stop()``, watchdog-channel "profstats")
  captures ``MXTPU_PROFSTATS_CAPTURE_S`` every
  ``MXTPU_PROFSTATS_INTERVAL_S``, skipping a cycle when an operator
  capture is in flight (``devstats.capture_in_progress()``) or a
  registered load probe reports overload (serving queue occupancy >
  ``MXTPU_PROFSTATS_MAX_LOAD``), and clamps the capture length to an
  overhead budget (``MXTPU_PROFSTATS_MAX_DUTY`` of the interval). Each
  capture folds into rolling aggregates exported as
  ``mxtpu_profile_op_seconds_total{model,category}`` /
  ``mxtpu_profile_device_idle_ratio`` and served ranked by
  ``GET /debug/hotspots`` (serving/server.py).

Summaries are remembered in a bounded, capture-id-keyed store so
``GET /debug/hotspots?capture=<id>`` keeps answering after
``devstats._prune`` deletes the capture directory itself.

Event model (verified against the CPU and TPU backends' chrome traces):
an XLA op execution is a ``ph == "X"`` event whose ``args`` carry
``hlo_op`` (op name, e.g. ``dot.4``) and ``hlo_module`` (program, e.g.
``jit_step``). Device-track events without args (TPU device lanes) fall
back to the pid heuristic tools/profile_bench.py proved out: a pid whose
process_name mentions a device, with ``jit_*`` / all-digit umbrella
events treated as containers, never leaves.
"""
from __future__ import annotations

import collections
import gzip
import io
import json
import logging
import os
import re
import threading

from .registry import counter, gauge

_LOG = logging.getLogger(__name__)

SCHEMA = "mxtpu-profstats-summary-v1"

# custom_call target markers the profiler/annotation layer may leave in
# an EXPORTED module (trace annotations, capture markers, named-scope
# host hints — e.g. a program traced under an active jax.profiler
# capture). These are pure metadata: the device never blocks on the
# host for them, so tools/hlolint's H003 host-round-trip rule exempts
# any custom_call target containing one of these substrings (imported
# there as the single source of truth — extend HERE when the profiler
# grows a new marker, never by loosening the H003 host regex).
ANNOTATION_TARGET_MARKERS = ("profiler", "annotation", "named_scope")

__all__ = [
    "SCHEMA", "ANNOTATION_TARGET_MARKERS",
    "categorize", "load_trace", "iter_trace_files",
    "summarize_events", "summarize_capture", "summarize_trace",
    "format_table", "capture_and_summarize", "remember", "get_summary",
    "brief",
    "summaries", "fold_summary", "hotspots", "reset_rolling",
    "add_load_probe", "remove_load_probe", "current_load",
    "start", "stop", "running", "run_once",
]

# ------------------------------------------------------------ metrics
_OP_SECONDS = counter(
    "mxtpu_profile_op_seconds_total",
    "Device self-seconds attributed by the profstats layer, by XLA op "
    "category, accumulated over every folded profiler capture. Model "
    "attribution follows the window's per-model share of "
    "mxtpu_device_dispatch_seconds_total ('-' when no serving traffic "
    "dispatched during the capture).", ("model", "category"))
_IDLE_RATIO = gauge(
    "mxtpu_profile_device_idle_ratio",
    "Device-idle fraction of the newest folded profiler capture window "
    "(1 - busy/window over the op tracks). High here with queued "
    "requests means host-side dispatch bubbles, not device saturation.")
_CAPTURES = counter(
    "mxtpu_profile_captures_total",
    "Profstats capture cycles by outcome: ok, empty (no op events), "
    "skipped_busy (operator capture in flight), skipped_load (probe "
    "over MXTPU_PROFSTATS_MAX_LOAD), error.", ("outcome",))

# ------------------------------------------------------ categorization
#: token sets checked IN ORDER — a conv fusion must rank as conv, not
#: elementwise; "convert" must not rank as conv (tokens, not substrings)
_COLLECTIVE_HINTS = ("all-reduce", "all-gather", "all-to-all",
                     "reduce-scatter", "collective", "permute")
_MATMUL_TOKENS = frozenset(("dot", "gemm", "matmul", "einsum"))
_CONV_TOKENS = frozenset(("conv", "convolution"))
_REDUCE_TOKENS = frozenset(("reduce",))
_COPY_TOKENS = frozenset((
    "copy", "transpose", "bitcast", "reshape", "concatenate", "pad",
    "slice", "gather", "scatter", "reverse", "tuple"))
_INFEED_TOKENS = frozenset(("infeed", "outfeed", "send", "recv", "host"))
_ELEMENTWISE_TOKENS = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "tanh", "exponential", "exp", "log", "logistic", "sigmoid", "relu",
    "erf", "rsqrt", "sqrt", "power", "negate", "sign", "abs", "floor",
    "ceil", "round", "clamp", "compare", "select", "broadcast", "iota",
    "convert", "constant", "rng", "map", "fusion", "and", "or", "not",
    "xor", "sine", "cosine", "atan2", "remainder", "shift", "popcnt",
    "is-finite", "expm1", "log1p"))

_TOKEN_RE = re.compile(r"[^a-z0-9]+")


def categorize(name):
    """Map an HLO op name (``dot.4``, ``loop_fusion.12``,
    ``reduce-window.3``) onto the coarse XLA category the hotspot table
    ranks by: matmul / conv / elementwise / reduce / copy / infeed /
    collective / other."""
    base = str(name).lower().lstrip("%")
    for hint in _COLLECTIVE_HINTS:
        if hint in base:
            return "collective"
    tokens = [t for t in _TOKEN_RE.split(base) if t and not t.isdigit()]
    tokset = frozenset(tokens)
    if tokset & _MATMUL_TOKENS:
        return "matmul"
    if tokset & _CONV_TOKENS:
        return "conv"
    if any(t.startswith("reduce") for t in tokens):
        return "reduce"
    if tokset & _COPY_TOKENS:
        return "copy"
    if tokset & _INFEED_TOKENS:
        return "infeed"
    if tokset & _ELEMENTWISE_TOKENS or any(
            t.startswith(("fusion", "fused")) for t in tokens):
        return "elementwise"
    return "other"


# -------------------------------------------------------- trace loading
def load_trace(path):
    """Load one chrome-trace file (plain or gzipped JSON) and return its
    event list. Raises ValueError on an unreadable/misshapen file — the
    per-capture walk downgrades that to a counted parse error."""
    opener = gzip.open if path.endswith(".gz") else open
    try:
        with opener(path, "rb") as f:
            data = json.load(io.TextIOWrapper(f, encoding="utf-8",
                                              errors="replace"))
    except (OSError, ValueError) as e:
        raise ValueError("unreadable trace %s: %s" % (path, e))
    events = data if isinstance(data, list) \
        else data.get("traceEvents") if isinstance(data, dict) else None
    if not isinstance(events, list):
        raise ValueError("trace %s has no traceEvents list" % path)
    return events


def iter_trace_files(capture_dir):
    """Every ``*.trace.json[.gz]`` under a capture dir, sorted (one per
    host in a multi-host capture)."""
    out = []
    for root, _dirs, files in os.walk(capture_dir):
        for fn in files:
            if fn.endswith((".trace.json", ".trace.json.gz")):
                out.append(os.path.join(root, fn))
    return sorted(out)


# ----------------------------------------------------- event aggregation
def _device_pids(events):
    """pids whose process_name marks a device lane (the TPU/GPU track
    heuristic folded in from tools/profile_bench.py)."""
    pids = set()
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "M":
            continue
        if ev.get("name") != "process_name":
            continue
        args = ev.get("args")
        label = str((args or {}).get("name", "")).lower() \
            if isinstance(args, dict) else ""
        if "tpu" in label or "gpu" in label or "/device" in label:
            pids.add(ev.get("pid"))
    return pids


def _merged_busy(intervals):
    """(busy_total, gaps) over a sorted-by-start interval list."""
    busy = 0.0
    gaps = []
    end = None
    for s, e in intervals:
        if end is None:
            end = e
            busy += e - s
            continue
        if s > end:
            gaps.append((end, s - end))
            busy += e - s
        else:
            busy += max(0.0, e - end)
        end = max(end, e)
    return busy, gaps


def summarize_events(events):
    """Aggregate one trace's events: per-op self time (umbrella events
    subtract their children), per-track busy/window, largest idle gaps.
    Malformed events are skipped and counted, never raised."""
    device_pids = _device_pids(events)
    tracks = collections.defaultdict(list)
    skipped = 0
    for ev in events:
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        try:
            ts = float(ev["ts"])
            dur = float(ev.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            skipped += 1
            continue
        if dur < 0:
            skipped += 1
            continue
        name = ev.get("name")
        if not isinstance(name, str):
            skipped += 1
            continue
        args = ev.get("args")
        hlo_op = args.get("hlo_op") if isinstance(args, dict) else None
        module = args.get("hlo_module") if isinstance(args, dict) else None
        if isinstance(hlo_op, str) and hlo_op:
            rec = [ts, dur, hlo_op, module, True, 0.0]
        elif ev.get("pid") in device_pids:
            # device lane without hlo args: jit_* / all-digit events are
            # whole-program umbrellas — containers for nesting, never ops
            umbrella = name.startswith("jit_") or name.isdigit()
            rec = [ts, dur, name, None, not umbrella, 0.0]
        else:
            continue          # host-side noise (threadpool, executor waits)
        tracks[(ev.get("pid"), ev.get("tid"))].append(rec)

    ops = {}                  # (op, module) -> [self_us, count, category]
    busy_us = 0.0
    window_lo = window_hi = None
    gaps = []
    n_tracks = 0
    for key in sorted(tracks, key=str):
        recs = sorted(tracks[key], key=lambda r: (r[0], -r[1]))
        stack = []            # open containers: rec refs, innermost last
        intervals = []
        for rec in recs:
            ts, dur = rec[0], rec[1]
            while stack and stack[-1][0] + stack[-1][1] <= ts:
                stack.pop()
            if stack:
                stack[-1][5] += dur     # direct parent loses self time
            stack.append(rec)
            if rec[4]:
                intervals.append((ts, ts + dur))
            lo, hi = ts, ts + dur
            window_lo = lo if window_lo is None else min(window_lo, lo)
            window_hi = hi if window_hi is None else max(window_hi, hi)
        track_has_ops = False
        for rec in recs:
            if not rec[4]:
                continue
            track_has_ops = True
            self_us = max(0.0, rec[1] - rec[5])
            k = (rec[2], rec[3])
            cell = ops.get(k)
            if cell is None:
                ops[k] = [self_us, 1, categorize(rec[2])]
            else:
                cell[0] += self_us
                cell[1] += 1
        if track_has_ops:
            n_tracks += 1
            intervals.sort()
            track_busy, track_gaps = _merged_busy(intervals)
            busy_us += track_busy
            gaps.extend(track_gaps)
    gaps.sort(key=lambda g: -g[1])
    return {
        "ops": ops, "skipped": skipped, "busy_us": busy_us,
        "window_lo": window_lo, "window_hi": window_hi,
        "tracks": n_tracks, "gaps": gaps[:10],
    }


def _merge_agg(total, part):
    for k, cell in part["ops"].items():
        tot = total["ops"].get(k)
        if tot is None:
            total["ops"][k] = list(cell)
        else:
            tot[0] += cell[0]
            tot[1] += cell[1]
    total["skipped"] += part["skipped"]
    total["busy_us"] += part["busy_us"]
    total["tracks"] += part["tracks"]
    for bound in ("window_lo", "window_hi"):
        v = part[bound]
        if v is None:
            continue
        cur = total[bound]
        pick = min if bound == "window_lo" else max
        total[bound] = v if cur is None else pick(cur, v)
    total["gaps"] = sorted(total["gaps"] + part["gaps"],
                           key=lambda g: -g[1])[:10]


def _to_summary(agg, traces, errors, capture_dir=None):
    window_us = 0.0
    if agg["window_lo"] is not None:
        window_us = max(0.0, agg["window_hi"] - agg["window_lo"])
    total_self = sum(cell[0] for cell in agg["ops"].values())
    ops = []
    cats = {}
    for (op, module), (self_us, count, cat) in agg["ops"].items():
        share = (self_us / total_self) if total_self > 0 else 0.0
        ops.append({"op": op, "module": module, "category": cat,
                    "self_us": self_us, "count": count, "share": share})
        cell = cats.setdefault(cat, {"self_us": 0.0, "count": 0,
                                     "share": 0.0})
        cell["self_us"] += self_us
        cell["count"] += count
        cell["share"] += share
    ops.sort(key=lambda o: (-o["self_us"], o["op"]))
    programs = {}
    for o in ops:
        if o["module"]:
            programs[o["module"]] = \
                programs.get(o["module"], 0.0) + o["self_us"]
    idle = None
    if window_us > 0 and agg["tracks"] > 0:
        idle = 1.0 - agg["busy_us"] / (window_us * agg["tracks"])
        idle = min(1.0, max(0.0, idle))
    return {
        "schema": SCHEMA,
        "capture_id": os.path.basename(capture_dir.rstrip(os.sep))
        if capture_dir else None,
        "dir": capture_dir,
        "traces": traces, "trace_errors": errors,
        "events": sum(c[1] for c in agg["ops"].values()),
        "skipped_events": agg["skipped"],
        "window_us": window_us,
        "device_busy_us": agg["busy_us"],
        "device_tracks": agg["tracks"],
        "device_idle_ratio": idle,
        "ops": ops,
        "categories": cats,
        "programs": programs,
        "gaps": [{"start_us": s, "dur_us": d} for s, d in agg["gaps"]],
    }


def _empty_agg():
    return {"ops": {}, "skipped": 0, "busy_us": 0.0, "window_lo": None,
            "window_hi": None, "tracks": 0, "gaps": []}


def summarize_capture(capture_dir):
    """Summarize every trace file under a capture dir into the shared
    summary dict (schema ``mxtpu-profstats-summary-v1``). Unreadable
    trace files are counted in ``trace_errors``; an empty or missing dir
    yields a valid zero summary rather than raising."""
    agg = _empty_agg()
    traces = errors = 0
    for path in iter_trace_files(capture_dir):
        try:
            events = load_trace(path)
        except ValueError:
            _LOG.debug("profstats: bad trace %s", path, exc_info=True)
            errors += 1
            continue
        traces += 1
        _merge_agg(agg, summarize_events(events))
    return _to_summary(agg, traces, errors, capture_dir=capture_dir)


def summarize_trace(path):
    """Summarize one trace file (the hand-me-a-.json.gz CLI path)."""
    agg = _empty_agg()
    _merge_agg(agg, summarize_events(load_trace(path)))
    return _to_summary(agg, 1, 0, capture_dir=os.path.dirname(path) or None)


# ------------------------------------------------------- devstats join
def _dispatch_overlap_us(t0_us, t1_us):
    """Wall microseconds spent inside finished serve:dispatch /
    train:step spans that overlap [t0_us, t1_us] (span start_us is
    epoch-anchored, same clock as profiler.now_us)."""
    from . import spans as spans_mod
    busy = 0.0
    n = 0
    for rec in spans_mod.snapshot():
        if rec.get("name") not in ("serve:dispatch", "train:step"):
            continue
        try:
            s = float(rec["start_us"])
            e = s + float(rec["dur_us"])
        except (KeyError, TypeError, ValueError):
            continue
        o = min(e, t1_us) - max(s, t0_us)
        if o > 0:
            busy += o
            n += 1
    return busy, n


def _attach_devstats(summary, before, after, wall_s, t0_us, t1_us):
    from . import devstats
    d = {k: max(0.0, after[k] - before[k])
         for k in ("flops", "bytes", "dispatch_s", "chip_s")}
    by_model = {}
    for m, v in after["by_model"].items():
        dv = v - before["by_model"].get(m, 0.0)
        if dv > 0:
            by_model[m] = dv
    peak = devstats.peaks()[0]
    exec_s = d["chip_s"] if d["chip_s"] > 0 else d["dispatch_s"]
    denom_s = exec_s if exec_s > 0 else wall_s
    mfu = (d["flops"] / (denom_s * peak)) if denom_s > 0 else 0.0
    cat_mfu = {c: mfu * info["share"]
               for c, info in summary["categories"].items()}
    for o in summary["ops"]:
        o["flops_est"] = o["share"] * d["flops"]
    dispatch_busy_us, n_spans = _dispatch_overlap_us(t0_us, t1_us)
    device_busy_us = summary["device_busy_us"]
    summary["devstats"] = {
        "window_s": wall_s,
        "flops": d["flops"], "bytes": d["bytes"],
        "dispatch_s": d["dispatch_s"], "chip_s": d["chip_s"],
        "mfu": mfu, "peak_flops": peak,
        "by_model": by_model,
        "category_mfu": cat_mfu,
    }
    summary["bubbles"] = {
        "spans": n_spans,
        "dispatch_busy_us": dispatch_busy_us,
        "device_busy_us": device_busy_us,
        # host-side bubble: wall time INSIDE dispatch spans the device
        # spent idle — the gap the MFU sprint chases when idle_ratio is
        # high under load
        "host_bubble_us": max(0.0, dispatch_busy_us - device_busy_us),
    }
    return summary


def capture_and_summarize(seconds, out_dir=None, fold=True):
    """One instrumented capture: snapshot the devstats dispatch counters,
    run ``devstats.capture_profile`` (ProfileCaptureBusy propagates),
    summarize the fresh dir, attach the devstats window join + bubble
    estimate, remember the summary under its capture id, and (daemon /
    route path) fold it into the rolling aggregates.

    Returns ``(capture_result, summary)``."""
    from .. import profiler
    from . import devstats
    before = devstats.dispatch_totals()
    t0 = profiler.now_us()
    out = devstats.capture_profile(seconds, out_dir=out_dir)
    t1 = profiler.now_us()
    summary = summarize_capture(out["dir"])
    summary["capture_id"] = out.get("capture_id") \
        or os.path.basename(out["dir"].rstrip(os.sep))
    after = devstats.dispatch_totals()
    _attach_devstats(summary, before, after, (t1 - t0) / 1e6, t0, t1)
    remember(summary)
    if fold:
        fold_summary(summary)
    return out, summary


# ------------------------------------------------- bounded summary store
_summaries_lock = threading.Lock()
_summaries = collections.OrderedDict()   # capture_id -> summary


def remember(summary):
    """Key a summary by capture id in the bounded store (newest
    MXTPU_PROFSTATS_SUMMARIES survive) — the store is what keeps
    ``GET /debug/hotspots?capture=<id>`` answering after devstats._prune
    deletes the capture dir itself."""
    from .. import config
    cid = summary.get("capture_id")
    if not cid:
        return
    bound = max(1, int(config.get_env("MXTPU_PROFSTATS_SUMMARIES")))
    with _summaries_lock:
        _summaries.pop(cid, None)
        _summaries[cid] = summary
        while len(_summaries) > bound:
            _summaries.popitem(last=False)


def get_summary(capture_id):
    with _summaries_lock:
        return _summaries.get(capture_id)


def brief(summary, top=15):
    """The trimmed view HTTP responses embed: top-``top`` ops plus the
    window facts (the full summary stays fetchable by capture id)."""
    out = {k: summary.get(k) for k in
           ("capture_id", "window_us", "events", "device_idle_ratio",
            "categories", "devstats", "bubbles")}
    out["ops"] = (summary.get("ops") or [])[:max(0, int(top))]
    return out


def summaries():
    """Remembered capture ids, oldest first."""
    with _summaries_lock:
        return list(_summaries)


# ------------------------------------------------------ rolling aggregates
_roll_lock = threading.Lock()
_roll = {"captures": 0, "ops": {}, "categories": {}, "busy_us": 0.0,
         "window_us": 0.0, "last_capture_id": None, "last_idle": None}


def fold_summary(summary):
    """Fold one capture summary into the rolling process aggregates and
    the exported series. Model attribution of the category seconds
    follows the window's per-model dispatch share; '-' when nothing
    dispatched during the window."""
    by_model = (summary.get("devstats") or {}).get("by_model") or {}
    total = sum(by_model.values())
    shares = {m: v / total for m, v in by_model.items()} if total > 0 \
        else {"-": 1.0}
    with _roll_lock:
        _roll["captures"] += 1
        _roll["busy_us"] += summary["device_busy_us"]
        _roll["window_us"] += summary["window_us"] \
            * max(1, summary["device_tracks"])
        _roll["last_capture_id"] = summary.get("capture_id")
        _roll["last_idle"] = summary.get("device_idle_ratio")
        for o in summary["ops"]:
            k = (o["op"], o["category"])
            cell = _roll["ops"].get(k)
            if cell is None:
                _roll["ops"][k] = [o["self_us"], o["count"]]
            else:
                cell[0] += o["self_us"]
                cell[1] += o["count"]
        for c, info in summary["categories"].items():
            _roll["categories"][c] = \
                _roll["categories"].get(c, 0.0) + info["self_us"]
    idle = summary.get("device_idle_ratio")
    if idle is not None:
        _IDLE_RATIO.set(idle)
    for c, info in summary["categories"].items():
        secs = info["self_us"] / 1e6
        for m, sh in shares.items():
            _OP_SECONDS.inc(secs * sh, model=m, category=c)


def hotspots(n=20):
    """The ranked rolling view GET /debug/hotspots serves: top-n ops and
    the per-category split accumulated over every folded capture."""
    with _roll_lock:
        total = sum(c[0] for c in _roll["ops"].values())
        ops = [{"op": op, "category": cat, "self_us": cell[0],
                "count": cell[1],
                "share": (cell[0] / total) if total > 0 else 0.0}
               for (op, cat), cell in _roll["ops"].items()]
        ops.sort(key=lambda o: (-o["self_us"], o["op"]))
        cats = {c: {"self_us": v,
                    "share": (v / total) if total > 0 else 0.0}
                for c, v in _roll["categories"].items()}
        busy, window = _roll["busy_us"], _roll["window_us"]
        return {
            "captures": _roll["captures"],
            "ops": ops[:max(0, int(n))],
            "categories": cats,
            "device_idle_ratio": _roll["last_idle"],
            "rolling_idle_ratio": (1.0 - busy / window)
            if window > 0 else None,
            "last_capture_id": _roll["last_capture_id"],
        }


def reset_rolling():
    """Forget the rolling aggregates (tests; the exported *_total
    counters keep their process-lifetime values by convention)."""
    with _roll_lock:
        _roll.update({"captures": 0, "ops": {}, "categories": {},
                      "busy_us": 0.0, "window_us": 0.0,
                      "last_capture_id": None, "last_idle": None})
    with _summaries_lock:
        _summaries.clear()


# ----------------------------------------------------------- load probes
_probes_lock = threading.Lock()
_load_probes = {}        # name -> fn() -> occupancy in [0, 1]


def add_load_probe(name, fn):
    """Register a load source the daemon consults before each capture
    (serving registries install their max queue-occupancy here). The
    daemon skips a cycle when any probe exceeds
    MXTPU_PROFSTATS_MAX_LOAD."""
    with _probes_lock:
        _load_probes[str(name)] = fn


def remove_load_probe(name):
    with _probes_lock:
        _load_probes.pop(str(name), None)


def current_load():
    """max over registered probes (0.0 with none; a raising probe reads
    as 0 — a broken probe must not pin the profiler off forever)."""
    with _probes_lock:
        probes = list(_load_probes.values())
    load = 0.0
    for fn in probes:
        try:
            load = max(load, float(fn()))
        except Exception:
            _LOG.debug("profstats load probe failed", exc_info=True)
    return load


# ---------------------------------------------------------------- daemon
_state_lock = threading.Lock()
_daemon_thread = None
_daemon_stop = None


def run_once(capture_s=None, interval_s=None):
    """One daemon cycle, callable directly (tests, the CI profstats
    stage): skip under an operator capture or overload, else capture +
    fold. Returns the summary, or None on a skipped/failed cycle; the
    outcome lands on mxtpu_profile_captures_total{outcome}."""
    from .. import config
    from . import devstats
    if capture_s is None:
        capture_s = float(config.get_env("MXTPU_PROFSTATS_CAPTURE_S"))
    if interval_s is None:
        interval_s = float(config.get_env("MXTPU_PROFSTATS_INTERVAL_S"))
    if devstats.capture_in_progress():
        _CAPTURES.inc(outcome="skipped_busy")
        return None
    max_load = float(config.get_env("MXTPU_PROFSTATS_MAX_LOAD"))
    if current_load() > max_load:
        _CAPTURES.inc(outcome="skipped_load")
        return None
    # overhead budget: the capture window may not exceed MAX_DUTY of the
    # interval — a fat capture knob must not turn the low-duty-cycle
    # profiler into a steady tracing tax
    max_duty = float(config.get_env("MXTPU_PROFSTATS_MAX_DUTY"))
    if interval_s > 0 and max_duty > 0:
        capture_s = min(capture_s, max(0.05, interval_s * max_duty))
    try:
        _out, summary = capture_and_summarize(capture_s)
    except devstats.ProfileCaptureBusy:
        _CAPTURES.inc(outcome="skipped_busy")
        return None
    except Exception:
        _LOG.warning("profstats capture cycle failed", exc_info=True)
        _CAPTURES.inc(outcome="error")
        return None
    _CAPTURES.inc(outcome="ok" if summary["events"] else "empty")
    return summary


def _daemon_loop(stop, interval_s, capture_s):
    from . import watchdog
    while not stop.wait(interval_s):
        watchdog.heartbeat("profstats")
        try:
            run_once(capture_s=capture_s, interval_s=interval_s)
        except Exception:
            _LOG.warning("profstats daemon cycle failed", exc_info=True)
        watchdog.heartbeat("profstats")


def start(interval_s=None, capture_s=None):
    """Start the continuous low-duty-cycle profiler daemon (idempotent;
    watchdog channel "profstats"). Defaults come from
    MXTPU_PROFSTATS_INTERVAL_S / MXTPU_PROFSTATS_CAPTURE_S."""
    from .. import config
    from . import watchdog
    global _daemon_thread, _daemon_stop
    if interval_s is None:
        interval_s = float(config.get_env("MXTPU_PROFSTATS_INTERVAL_S"))
    if capture_s is None:
        capture_s = float(config.get_env("MXTPU_PROFSTATS_CAPTURE_S"))
    interval_s = max(0.05, interval_s)
    with _state_lock:
        if _daemon_thread is not None and _daemon_thread.is_alive():
            return False
        stop_ev = threading.Event()
        t = threading.Thread(
            target=_daemon_loop, args=(stop_ev, interval_s, capture_s),
            name="mxtpu-profstats", daemon=True)
        _daemon_stop = stop_ev
        _daemon_thread = t
        # generous quiet budget: a cycle = capture + parse; three missed
        # intervals means the daemon is wedged, not slow
        watchdog.register("profstats",
                          quiet_s=3 * interval_s + 60.0)
        watchdog.heartbeat("profstats")
        t.start()
        return True


def _stop_locked():
    from . import watchdog
    global _daemon_thread, _daemon_stop
    t, stop_ev = _daemon_thread, _daemon_stop
    _daemon_thread = _daemon_stop = None
    if stop_ev is not None:
        stop_ev.set()
    if t is not None and t.is_alive():
        t.join(timeout=5.0)
    watchdog.unregister("profstats")
    # detach the continuous signal: a stopped daemon must not export its
    # last idle ratio forever (the op-seconds counters stay — process-
    # lifetime cumulative by Prometheus convention)
    _IDLE_RATIO.remove()


def stop():
    """Stop the daemon and detach its continuous gauge series."""
    with _state_lock:
        _stop_locked()


def running():
    t = _daemon_thread
    return t is not None and t.is_alive()


# ------------------------------------------------------------ formatting
def format_table(summary, top=40):
    """The ranked-hotspot table both tools/profsum.py and
    tools/profile_bench.py print (one renderer, one parser)."""
    lines = []
    ops = summary.get("ops") or []
    lines.append("%4s  %12s  %6s  %8s  %-12s %s"
                 % ("rank", "self-ms", "%dev", "count", "category",
                    "op [module]"))
    for i, o in enumerate(ops[:max(0, int(top))], 1):
        label = o["op"] + (" [%s]" % o["module"] if o.get("module") else "")
        lines.append("%4d  %12.3f  %5.1f%%  %8d  %-12s %s"
                     % (i, o["self_us"] / 1e3, 100.0 * o["share"],
                        o["count"], o["category"], label))
    if not ops:
        lines.append("(no op events)")
    cats = summary.get("categories") or {}
    if cats:
        split = ", ".join(
            "%s %.1f%%" % (c, 100.0 * info["share"]) for c, info in
            sorted(cats.items(), key=lambda kv: -kv[1]["self_us"]))
        lines.append("categories: %s" % split)
    idle = summary.get("device_idle_ratio")
    if idle is not None:
        lines.append("device idle: %.1f%% of a %.1f ms window "
                     "(%d track(s))"
                     % (100.0 * idle, summary.get("window_us", 0.0) / 1e3,
                        summary.get("device_tracks", 0)))
    dv = summary.get("devstats")
    if dv:
        lines.append("window MFU %.4f (peak %.3g FLOP/s); category MFU: %s"
                     % (dv["mfu"], dv["peak_flops"],
                        ", ".join("%s %.4f" % (c, v) for c, v in
                                  sorted(dv["category_mfu"].items(),
                                         key=lambda kv: -kv[1]))))
    bub = summary.get("bubbles")
    if bub and bub["spans"]:
        lines.append("dispatch bubbles: %.3f ms host-side inside %d "
                     "dispatch/train spans (device busy %.3f ms)"
                     % (bub["host_bubble_us"] / 1e3, bub["spans"],
                        bub["device_busy_us"] / 1e3))
    return "\n".join(lines)
