"""Shared bounded-ring machinery for the diagnostics buffers (the span
ring and the flight recorder).

One design, two users: a ``deque(maxlen=...)`` sized lazily from a typed
config knob, GIL-atomic lock-free appends (writers never block and never
raise into the instrumented path — a malformed env value degrades to a
dropped record, not a crashed train step), and a retry-on-mutation
snapshot so readers never block writers either.
"""
from __future__ import annotations

import threading
from collections import deque

__all__ = ["BoundedRing"]


class BoundedRing:
    """Lock-cheap bounded event ring sized by a config env var.

    - ``append`` is the hot-path write: deque.append under the GIL, no
      lock, and exception-proof (the ring must never be able to fail the
      path it observes — first use parses the env knob, which can raise).
    - ``snapshot`` copies without locking writers out: a concurrent
      append can invalidate the iteration (RuntimeError), so it retries
      a few times and degrades to [] rather than stalling anyone.
    - ``reset`` drops the buffer AND re-reads the size knob (test
      isolation).
    """

    def __init__(self, size_env_var, min_size=1):
        self._size_env_var = size_env_var
        self._min_size = min_size
        self._create_lock = threading.Lock()   # guards (re)creation only
        self._ring = None

    def _get(self):
        if self._ring is None:
            from .. import config
            with self._create_lock:
                if self._ring is None:
                    self._ring = deque(maxlen=max(
                        self._min_size,
                        config.get_env(self._size_env_var)))
        return self._ring

    def append(self, item):
        try:
            self._get().append(item)
        except Exception:
            pass        # never raise into the instrumented path

    def snapshot(self):
        ring = self._ring
        if ring is None:
            return []
        for _ in range(8):
            try:
                return list(ring)
            except RuntimeError:    # deque mutated mid-iteration: retry
                continue
        return []

    def __len__(self):
        ring = self._ring
        return len(ring) if ring is not None else 0

    def reset(self):
        with self._create_lock:
            self._ring = None
