"""Request-scoped tracing: one ID per external request, carried from the
HTTP front-end through the batcher queue into the profiler's chrome-trace
events, so one slow request can be followed queue -> bucket -> device in a
single trace dump.

The ID itself is a short opaque hex string. Propagation is explicit (the
serving ``_Request`` carries it through the worker-thread handoff — a
contextvar would be lost at the queue boundary), but a thread-local
*current* slot is kept for code that wants ambient access on the thread
that owns the request (e.g. user servables logging per-request).
"""
from __future__ import annotations

import os
import threading

__all__ = ["new_request_id", "REQUEST_ID_HEADER", "current_request_id",
           "set_current_request_id", "request_scope"]

#: HTTP header the serving front-end reads (client-supplied IDs win, so a
#: caller's existing trace context is preserved) and echoes on responses.
REQUEST_ID_HEADER = "X-Request-Id"

_local = threading.local()


def new_request_id():
    """16 hex chars from os.urandom — no global counter lock, no PRNG
    state shared with model seeding."""
    return os.urandom(8).hex()


def current_request_id():
    """The ambient request ID on this thread, or None."""
    return getattr(_local, "request_id", None)


def set_current_request_id(request_id):
    _local.request_id = request_id


class request_scope:
    """``with request_scope(rid):`` — sets the ambient ID, restoring the
    previous one on exit (nesting-safe for re-entrant serving paths)."""

    def __init__(self, request_id):
        self.request_id = request_id

    def __enter__(self):
        self._old = current_request_id()
        set_current_request_id(self.request_id)
        return self.request_id

    def __exit__(self, *exc):
        set_current_request_id(self._old)
