"""Stall watchdog: heartbeats from the framework's worker loops plus a
monitor thread that answers *why is it stuck* — without killing anything.

Instrumented loops (TrainStep, the serving batcher workers, io prefetch
threads) call ``heartbeat(name)`` once per iteration: one dict store, no
lock, cheap enough for every step. The monitor thread wakes every
``MXTPU_WATCHDOG_POLL_S`` and, when a registered channel has been quiet
for ``MXTPU_WATCHDOG_QUIET_S`` (per-channel override via
``register(quiet_s=)``), emits ONE stall report for that stall:

- all-thread stacks (``sys._current_frames`` + ``traceback`` — the
  in-process, serveable form of a faulthandler dump),
- the flight-recorder tail (what the process was doing as it went quiet),

appended to ``MXTPU_WATCHDOG_FILE`` (when set) and logged; the newest
report stays readable at ``last_report()`` / ``GET /debug/stacks``. The
channel re-arms when its heartbeat resumes, so a recurring stall produces
one report per episode, not one per poll. The process is never killed:
the watchdog diagnoses, the operator (or orchestrator) decides.

Lifecycle: ``start()`` spawns the (daemonized) monitor; ``stop()`` joins
it. ``MXTPU_WATCHDOG=1`` autostarts at package import. A worker that
exits cleanly must ``unregister`` its channel (the batcher/prefetcher
close paths do) — a silent channel is indistinguishable from a stalled
one, by design.
"""
from __future__ import annotations

import logging
import sys
import threading
import time
import traceback

from . import flightrec
from .registry import counter

__all__ = ["heartbeat", "register", "unregister", "channels",
           "format_stacks", "last_report", "start", "stop", "running"]

_LOG = logging.getLogger(__name__)

_STALLS = counter(
    "mxtpu_watchdog_stalls_total",
    "Stall episodes detected per heartbeat channel (one per episode, "
    "not per poll).", ("channel",))


class _Channel:
    __slots__ = ("name", "last", "quiet_s", "stalled")

    def __init__(self, name, quiet_s=None):
        self.name = name
        self.last = time.perf_counter()
        self.quiet_s = quiet_s        # None: the watchdog default
        self.stalled = False


_channels = {}                       # name -> _Channel
#: guards _channels MAP mutation + iteration (R010: register runs on
#: worker threads — io prefetchers register their own channel — while
#: the monitor iterates). The per-beat fast path stays lock-free: it
#: mutates the _Channel OBJECT (two attribute stores), never the map.
_channels_lock = threading.Lock()
_state_lock = threading.Lock()       # monitor lifecycle only
_report_lock = threading.Lock()      # guards _last_report (R010)
_thread = None
_stop_event = None
_last_report = None                  # newest stall report text


def register(name, quiet_s=None):
    """Declare a heartbeat channel (optionally with its own quiet bound —
    an io prefetcher that legally blocks for minutes should not page at a
    train step's threshold). Idempotent; resets the beat."""
    with _channels_lock:
        ch = _channels.get(name)
        if ch is None or ch.quiet_s != quiet_s:
            _channels[name] = _Channel(name, quiet_s)
        else:
            ch.last = time.perf_counter()
            ch.stalled = False
    return name


def unregister(name):
    """Remove a channel (worker exiting cleanly): silence from a gone
    worker is not a stall."""
    with _channels_lock:
        _channels.pop(name, None)


def heartbeat(name):
    """One beat: a dict lookup and two attribute stores — hot-loop cheap,
    no lock on the steady-state path. Only the first beat of an unknown
    channel takes the map lock to auto-register it."""
    ch = _channels.get(name)
    if ch is None:
        with _channels_lock:
            ch = _channels.get(name)
            if ch is None:
                ch = _channels[name] = _Channel(name)
    ch.last = time.perf_counter()
    ch.stalled = False


def _channel_snapshot():
    """Consistent copy of the channel map for iteration (monitor poll,
    liveness view) — readers never see a half-built map entry."""
    with _channels_lock:
        return dict(_channels)


def channels():
    """{name: seconds_since_last_beat} — the liveness snapshot
    ``GET /debug/stacks`` includes."""
    now = time.perf_counter()
    return {name: now - ch.last
            for name, ch in _channel_snapshot().items()}


# ---------------------------------------------------------------- dumping
def format_stacks():
    """All-thread stack dump (sys._current_frames), thread names resolved —
    the operator-facing 'where is everyone' view."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    lines = []
    for ident, frame in sorted(frames.items()):
        t = by_ident.get(ident)
        name = t.name if t is not None else "?"
        daemon = " daemon" if t is not None and t.daemon else ""
        lines.append("--- thread %r (ident %d%s) ---" % (name, ident,
                                                         daemon))
        lines.extend(l.rstrip("\n")
                     for l in traceback.format_stack(frame))
    return "\n".join(lines) + "\n"


def _build_report(stalled_names, quiet):
    beats = channels()
    head = ["=== mxtpu stall report ===",
            "stalled channel(s): %s (quiet > %.3fs)"
            % (", ".join(sorted(stalled_names)), quiet),
            "heartbeats (s since last beat): %s"
            % ", ".join("%s=%.3f" % (n, s)
                        for n, s in sorted(beats.items())),
            "", "--- all-thread stacks ---"]
    rec_tail = flightrec.format_tail(100)
    return "\n".join(head) + "\n" + format_stacks() \
        + "\n--- flight recorder tail ---\n" \
        + (rec_tail if rec_tail else "(empty)\n")


def last_report():
    """The newest stall report text, or None if no stall was seen."""
    with _report_lock:
        return _last_report


def _emit_report(report, path):
    global _last_report
    # file first, in-memory publish last: last_report() flipping
    # non-None is the signal readers key on, so every other artifact of
    # the report must already be visible when it does (same ordering
    # discipline as the stall counter below)
    if path:
        try:
            with open(path, "a") as f:
                f.write(report + "\n")
        except Exception:
            _LOG.debug("watchdog report write to %r failed", path,
                       exc_info=True)
    with _report_lock:
        _last_report = report
    _LOG.error("stall detected — report follows\n%s", report)


# ---------------------------------------------------------------- monitor
def _monitor(stop, quiet_default, poll_s, path):
    while not stop.wait(poll_s):
        try:
            now = time.perf_counter()
            newly_stalled = []
            for ch in _channel_snapshot().values():
                bound = ch.quiet_s if ch.quiet_s is not None \
                    else quiet_default
                if now - ch.last > bound:
                    if not ch.stalled:
                        ch.stalled = True      # once per stall episode
                        newly_stalled.append(ch.name)
                # (heartbeat() itself re-arms ch.stalled on resume)
            if newly_stalled:
                flightrec.record("watchdog_stall",
                                 channels=sorted(newly_stalled))
                _emit_report(_build_report(newly_stalled, quiet_default),
                             path)
                # counter LAST: anything keyed on mxtpu_watchdog_stalls_
                # total (tests, operator automation) must find the report
                # already published when the increment becomes visible
                for name in newly_stalled:
                    _STALLS.inc(channel=name)
        except Exception:
            # the diagnoser must outlive whatever it is diagnosing — but
            # a broken poll loop must not be silent either (R005)
            _LOG.debug("watchdog poll failed", exc_info=True)


def start(quiet_s=None, poll_s=None, path=None):
    """Start (or restart with new settings) the monitor thread. Defaults
    come from MXTPU_WATCHDOG_{QUIET_S,POLL_S,FILE}. Returns the thread."""
    from .. import config
    global _thread, _stop_event
    if quiet_s is None:
        quiet_s = config.get_env("MXTPU_WATCHDOG_QUIET_S")
    if poll_s is None:
        poll_s = config.get_env("MXTPU_WATCHDOG_POLL_S")
    if path is None:
        path = config.get_env("MXTPU_WATCHDOG_FILE")
    quiet_s = max(0.05, float(quiet_s))
    poll_s = max(0.01, float(poll_s))
    with _state_lock:
        _stop_locked()
        stop_ev = threading.Event()
        t = threading.Thread(target=_monitor,
                             args=(stop_ev, quiet_s, poll_s, path),
                             daemon=True, name="mxtpu-watchdog")
        _stop_event, _thread = stop_ev, t
        t.start()
    return t


def _stop_locked():
    global _thread, _stop_event
    stop_ev, t = _stop_event, _thread
    _stop_event = _thread = None
    if stop_ev is not None:
        stop_ev.set()
        if t is not None:
            t.join(timeout=5.0)


def stop():
    """Stop and join the monitor (R007: the daemon flag is a crash-exit
    backstop, not a lifecycle plan)."""
    with _state_lock:
        _stop_locked()


def running():
    t = _thread
    return t is not None and t.is_alive()
