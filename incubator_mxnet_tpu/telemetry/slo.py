"""SLO engine: per-model objectives, error-budget accounting, and
multi-window burn-rate alerts (the SRE-workbook alerting model on the
serving path).

The serving front-end feeds every terminal request outcome into a
per-model good/bad ledger (2xx good; 429/504/5xx bad; other 4xx are
client mistakes and count for neither side), from which three operator
facts are derived:

- **error budget remaining** over a sliding window
  (``MXTPU_SLO_WINDOW_S``): 1.0 = untouched, 0.0 = exhausted; refills as
  bad events age out of the window;
- **burn rates** per window: ``bad_fraction / (1 - target)`` — 1.0 means
  the budget is being spent exactly as fast as the objective allows,
  14.4 means a 30-day budget would be gone in ~2 days;
- **alert pairs** (``MXTPU_SLO_WINDOWS``, SHORT:LONG seconds): a pair
  breaches only when BOTH windows' burn rates exceed its threshold
  (``MXTPU_SLO_FAST_BURN`` for the first pair, ``MXTPU_SLO_SLOW_BURN``
  for the rest) — the short window detects fast, the long window
  suppresses blips. Each pair runs a pending -> firing -> resolved state
  machine with hysteresis: firing requires the breach to hold for
  ``pending_s``, resolving requires it to stay clear for ``resolve_s``,
  so a single good sample never flaps an active alert.

Two objective kinds per model: ``availability`` (a 2xx IS good) and,
when a threshold is configured (``MXTPU_SLO_LATENCY_MS`` or
``define(kind="latency", latency_ms=...)``), ``latency`` (a 2xx slower
than the threshold spends latency budget; server-class failures spend it
too — a request that never answered is not fast).

Every piece of time arithmetic runs on an **injectable clock** (a
``clock()`` -> monotonic-seconds callable, default ``time.monotonic``),
so the whole engine — window aging, budget refill, alert lifecycle — is
unit-testable with zero real sleeps (the loadgen fake-clock pattern).

Surfaces:

- gauges ``mxtpu_slo_burn_rate{slo,window}`` /
  ``mxtpu_slo_budget_remaining{slo}`` / ``mxtpu_slo_alert_firing
  {slo,pair}`` (sampled live at scrape time via gauge callbacks, so a
  scrape also advances the alert state machine — resolution does not
  need traffic), counters ``mxtpu_slo_events_total{slo,outcome}``;
- flightrec events (``slo_alert``) on every state transition — alert
  history survives in the black-box tape;
- ``GET /debug/slo`` (serving/server.py) renders ``REGISTRY.describe()``.

SLO objects are seeded per served model by the serving registry
(``ensure_model``) and detached when the model's batcher closes — a
dead model must not keep exporting a frozen burn rate.
"""
from __future__ import annotations

import logging
import math
import threading
import time

from . import flightrec
from .registry import counter as _counter, gauge as _gauge

__all__ = ["SLO", "SLORegistry", "REGISTRY", "AlertPair", "observe",
           "ensure_model", "describe"]

_LOG = logging.getLogger(__name__)

_EVENTS = _counter(
    "mxtpu_slo_events_total",
    "Eligible request outcomes fed into an SLO's good/bad ledger "
    "(2xx good; 429/504/5xx bad; other 4xx not counted; a latency SLO "
    "additionally counts slow 2xx as bad) — docs/OBSERVABILITY.md "
    "'SLOs and tenants'.", ("slo", "outcome"))
_BURN = _gauge(
    "mxtpu_slo_burn_rate",
    "Error-budget burn rate over one sliding window: bad_fraction / "
    "(1 - target). 1.0 spends the budget exactly at the objective rate; "
    "the alert pairs compare this against MXTPU_SLO_FAST_BURN / "
    "MXTPU_SLO_SLOW_BURN. Sampled live at scrape time.",
    ("slo", "window"))
_BUDGET = _gauge(
    "mxtpu_slo_budget_remaining",
    "Fraction of the error budget left over the MXTPU_SLO_WINDOW_S "
    "sliding window (1 = untouched, 0 = exhausted; clamped at 0). "
    "Refills as bad events age out of the window.", ("slo",))
_FIRING = _gauge(
    "mxtpu_slo_alert_firing",
    "1 while this SLO's alert-window pair is in the firing state, else "
    "0 (pending/resolved/inactive). State transitions also land in the "
    "flight recorder as slo_alert events.", ("slo", "pair"))


def _default_clock():
    return time.monotonic()


def _eligible(code):
    """Is this outcome SLO-eligible for ANY objective kind? (2xx, 429,
    504, 5xx; other 4xx are the client's mistake.) The gate that keeps
    auto-seeding from minting SLO objects for attacker-controlled model
    names: a name that never loaded can only ever produce 400/404."""
    code = int(code)
    return 200 <= code < 300 or code == 429 or code == 504 \
        or 500 <= code < 600


# --------------------------------------------------------------------- ledger
class _Ledger:
    """Bucketed good/bad ring covering the longest window an SLO reads.

    Buckets are ``bucket_s`` wide (resolution, floored so the ring never
    exceeds ~4096 slots even for a 6 h window); ``add`` lands in the
    bucket the clock says is current, zeroing any buckets the clock
    skipped — so a window sum over the newest ``ceil(W / bucket_s)``
    buckets is exact to one bucket of quantization at the boundary.
    Caller (SLO) holds the lock; the ledger itself is lock-free.
    """

    def __init__(self, max_window_s, resolution_s=0.25):
        self.bucket_s = max(float(resolution_s), float(max_window_s) / 4096.0)
        self.slots = int(math.ceil(float(max_window_s) / self.bucket_s)) + 1
        self.good = [0] * self.slots
        self.bad = [0] * self.slots
        self._head = None          # absolute bucket index of the newest add

    def _advance(self, now):
        idx = int(now // self.bucket_s)
        if self._head is None:
            self._head = idx
            return idx
        if idx > self._head:
            # zero every bucket the clock skipped (bounded by ring size)
            for i in range(self._head + 1,
                           min(idx, self._head + self.slots) + 1):
                self.good[i % self.slots] = 0
                self.bad[i % self.slots] = 0
            self._head = idx
        return self._head

    def add(self, good, now):
        idx = self._advance(now)
        if good:
            self.good[idx % self.slots] += 1
        else:
            self.bad[idx % self.slots] += 1

    def window_counts(self, window_s, now):
        """(good, bad) totals over the trailing ``window_s`` seconds."""
        idx = self._advance(now)
        k = min(self.slots, int(math.ceil(float(window_s) / self.bucket_s)))
        g = b = 0
        for i in range(idx - k + 1, idx + 1):
            g += self.good[i % self.slots]
            b += self.bad[i % self.slots]
        return g, b


# ---------------------------------------------------------------- alert pairs
class AlertPair:
    """One SRE-workbook multi-window alert: breach = burn(short) AND
    burn(long) above ``threshold``; pending -> firing after ``pending_s``
    of sustained breach, firing -> resolved after ``resolve_s`` of
    sustained clear (the hysteresis that stops a single good sample from
    flapping an active alert). ``resolved`` is sticky until the next
    breach restarts the cycle at pending."""

    def __init__(self, name, short_s, long_s, threshold,
                 pending_s=0.0, resolve_s=None):
        self.name = name
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        if self.long_s < self.short_s:
            raise ValueError(
                "alert pair %r: long window %.0fs < short window %.0fs"
                % (name, self.long_s, self.short_s))
        self.threshold = float(threshold)
        self.pending_s = float(pending_s)
        self.resolve_s = (float(resolve_s) if resolve_s is not None
                          else self.short_s / 2.0)
        self.state = "inactive"
        self.since = None           # clock time of the last state change
        self._clear_since = None    # breach-clear streak start while firing

    def evaluate(self, burn_short, burn_long, now):
        """Advance the state machine; returns the list of states entered
        (empty when nothing changed). A zero ``pending_s`` still passes
        through pending — the full pending -> firing lifecycle lands in
        the event stream — because the long window already provides the
        sustain requirement the pending timer would otherwise add."""
        breach = burn_short > self.threshold and burn_long > self.threshold
        entered = []
        if self.state in ("inactive", "resolved"):
            if breach:
                self.state, self.since = "pending", now
                entered.append("pending")
        if self.state == "pending":
            if not breach:
                if "pending" not in entered:   # a held pending that cleared
                    self.state, self.since = "inactive", now
                    entered.append("inactive")
            elif now - self.since >= self.pending_s:
                self.state, self.since = "firing", now
                self._clear_since = None
                entered.append("firing")
        elif self.state == "firing":
            if breach:
                self._clear_since = None
            else:
                if self._clear_since is None:
                    self._clear_since = now
                if now - self._clear_since >= self.resolve_s:
                    self.state, self.since = "resolved", now
                    entered.append("resolved")
        return entered

    def describe(self, now):
        return {"pair": self.name, "short_s": self.short_s,
                "long_s": self.long_s, "threshold": self.threshold,
                "state": self.state,
                "state_age_s": (now - self.since
                                if self.since is not None else None)}


def _parse_windows(spec):
    """'300:3600,3600:21600' -> [('fast', 300.0, 3600.0),
    ('slow', 3600.0, 21600.0), ('slow2', ...)]."""
    pairs = []
    for i, part in enumerate(str(spec).split(",")):
        part = part.strip()
        if not part:
            continue
        short, sep, long_ = part.partition(":")
        if not sep:
            raise ValueError("bad MXTPU_SLO_WINDOWS pair %r "
                             "(want SHORT:LONG seconds)" % part)
        name = "fast" if i == 0 else ("slow" if i == 1 else "slow%d" % i)
        pairs.append((name, float(short), float(long_)))
    if not pairs:
        raise ValueError("MXTPU_SLO_WINDOWS is empty")
    return pairs


# ------------------------------------------------------------------------ SLO
class SLO:
    """One objective over one model's request stream.

    ``kind`` is ``"availability"`` (2xx good), ``"latency"`` (2xx good
    only when its end-to-end latency is <= ``latency_ms``), or
    ``"inter_token"`` (same threshold arithmetic as latency, but the
    outcome stream is PER GENERATED TOKEN, not per request — the decode
    engine feeds one observation per token gap, so the target reads as
    "p-target of inter-token gaps under latency_ms"; objectives are
    minted per tenant by serving/generate.py under
    ``MXTPU_GEN_SLO_INTER_TOKEN_MS``). All kinds count 429/504/5xx as
    bad and ignore other 4xx. All time arithmetic uses the injected
    ``clock``.
    """

    def __init__(self, name, model, kind="availability", target=None,
                 latency_ms=None, window_s=None, windows=None,
                 fast_burn=None, slow_burn=None, pending_s=0.0,
                 resolve_s=None, resolution_s=0.25, clock=None):
        from .. import config
        if kind not in ("availability", "latency", "inter_token"):
            raise ValueError("unknown SLO kind %r" % kind)
        if kind in ("latency", "inter_token") and latency_ms is None:
            raise ValueError("%s SLO %r needs latency_ms" % (kind, name))
        self.name = name
        self.model = model
        self.kind = kind
        self.target = float(target if target is not None
                            else config.get_env("MXTPU_SLO_TARGET"))
        if not 0.0 < self.target < 1.0:
            raise ValueError("SLO target must be in (0, 1), got %r"
                             % self.target)
        self.latency_ms = (float(latency_ms)
                           if latency_ms is not None else None)
        self.window_s = float(window_s if window_s is not None
                              else config.get_env("MXTPU_SLO_WINDOW_S"))
        spec = (windows if windows is not None
                else config.get_env("MXTPU_SLO_WINDOWS"))
        parsed = _parse_windows(spec) if isinstance(spec, str) else [
            ("fast" if i == 0 else ("slow" if i == 1 else "slow%d" % i),
             float(s), float(l)) for i, (s, l) in enumerate(spec)]
        if fast_burn is None:
            fast_burn = config.get_env("MXTPU_SLO_FAST_BURN")
        if slow_burn is None:
            slow_burn = config.get_env("MXTPU_SLO_SLOW_BURN")
        self.pairs = [AlertPair(nm, s, l,
                                fast_burn if nm == "fast" else slow_burn,
                                pending_s=pending_s, resolve_s=resolve_s)
                      for nm, s, l in parsed]
        self.windows = sorted({w for p in self.pairs
                               for w in (p.short_s, p.long_s)})
        self.clock = clock if clock is not None else _default_clock
        max_window = max([self.window_s] + self.windows)
        self._lock = threading.Lock()
        self._ledger = _Ledger(max_window, resolution_s=resolution_s)
        self._eval_bucket = None    # last bucket the pairs were evaluated in

    # ------------------------------------------------------------- outcomes
    def classify(self, code, latency_ms=None):
        """'good' / 'bad' / None (not an SLO-eligible outcome)."""
        code = int(code)
        if 200 <= code < 300:
            if (self.kind in ("latency", "inter_token")
                    and latency_ms is not None
                    and latency_ms > self.latency_ms):
                return "bad"
            return "good"
        if code == 429 or code == 504 or 500 <= code < 600:
            return "bad"
        return None                 # 400/404/...: the client's mistake

    def observe(self, code, latency_ms=None, now=None):
        """Feed one terminal outcome; returns the list of alert
        transitions it caused (the registry turns them into flightrec
        events). Evaluation is amortized to once per ledger bucket."""
        outcome = self.classify(code, latency_ms)
        if outcome is None:
            return []
        if now is None:
            now = self.clock()
        with self._lock:
            self._ledger.add(outcome == "good", now)
        try:
            _EVENTS.inc(slo=self.name, outcome=outcome)
        except Exception:
            _LOG.debug("slo event counter update failed", exc_info=True)
        return self.evaluate(now)

    # ---------------------------------------------------------------- reads
    def burn_rate(self, window_s, now=None):
        """bad_fraction over the window / (1 - target); 0 with no events."""
        if now is None:
            now = self.clock()
        with self._lock:
            g, b = self._ledger.window_counts(window_s, now)
        total = g + b
        if not total:
            return 0.0
        return (b / total) / (1.0 - self.target)

    def budget_remaining(self, now=None):
        """1 - spent fraction of the window's error budget, clamped at 0
        (a fully-good window reads 1.0; so does an empty one)."""
        if now is None:
            now = self.clock()
        with self._lock:
            g, b = self._ledger.window_counts(self.window_s, now)
        total = g + b
        if not total:
            return 1.0
        allowed = total * (1.0 - self.target)
        return max(0.0, 1.0 - b / allowed)

    def evaluate(self, now=None, force=False):
        """Advance every alert pair; returns [(pair, new_state,
        burn_short, burn_long), ...] for pairs that changed state.
        Amortized: repeat calls within one ledger bucket are no-ops
        unless ``force`` (scrape paths force, so resolution never waits
        for traffic)."""
        if now is None:
            now = self.clock()
        with self._lock:
            bucket = int(now // self._ledger.bucket_s)
            if not force and bucket == self._eval_bucket:
                return []
            self._eval_bucket = bucket
            burns = {}
            for w in self.windows:
                g, b = self._ledger.window_counts(w, now)
                total = g + b
                burns[w] = ((b / total) / (1.0 - self.target)
                            if total else 0.0)
            transitions = []
            for p in self.pairs:
                bs, bl = burns[p.short_s], burns[p.long_s]
                for state in p.evaluate(bs, bl, now):
                    transitions.append((p, state, bs, bl))
        return transitions

    def describe(self, now=None, evaluate=True):
        """Snapshot dict. ``evaluate=False`` lets a caller that already
        ran evaluate(now) itself (and emitted the transitions) skip the
        re-evaluation — a second forced pass with a later ``now`` could
        cross a state edge whose transition nobody would ever emit."""
        if now is None:
            now = self.clock()
        if evaluate:
            self.evaluate(now, force=True)
        out = {"name": self.name, "model": self.model, "kind": self.kind,
               "target": self.target,
               "window_s": self.window_s,
               "budget_remaining": self.budget_remaining(now),
               "burn_rates": {"%gs" % w: self.burn_rate(w, now)
                              for w in self.windows},
               "alerts": [p.describe(now) for p in self.pairs]}
        if self.latency_ms is not None:
            out["latency_ms"] = self.latency_ms
        return out


# ------------------------------------------------------------------ registry
class SLORegistry:
    """Name -> SLO map + the gauge/flightrec publication wiring.

    Only a registry constructed with ``publish=True`` (the process-wide
    ``REGISTRY``) binds the shared telemetry gauges — unit tests build
    private instances with a fake clock and read the SLO objects
    directly, so two registries never fight over one gauge series.
    """

    def __init__(self, clock=None, publish=False):
        self._lock = threading.Lock()
        self._slos = {}             # name -> SLO
        self._by_model = {}         # model -> [SLO, ...]
        self._gauge_fns = {}        # slo name -> [bound callbacks]
        self.clock = clock
        self.publish = publish

    # ----------------------------------------------------------- definition
    def define(self, name, model, **kw):
        """Get-or-create (idempotent by name; a re-define returns the
        existing SLO unchanged — ledgers must survive hot reloads)."""
        with self._lock:
            s = self._slos.get(name)
            if s is not None:
                return s
            kw.setdefault("clock", self.clock)
            s = SLO(name, model, **kw)
            self._slos[name] = s
            self._by_model.setdefault(model, []).append(s)
        if self.publish:
            self._publish(s)
        return s

    def ensure_model(self, model):
        """Seed the default objectives for one served model: availability
        always; latency too when MXTPU_SLO_LATENCY_MS is set. Called by
        the serving registry at model-entry creation."""
        from .. import config
        out = [self.define("%s/availability" % model, model,
                           kind="availability")]
        lat = config.get_env("MXTPU_SLO_LATENCY_MS")
        if lat is not None:
            out.append(self.define("%s/latency" % model, model,
                                   kind="latency", latency_ms=lat))
        return out

    def _publish(self, s):
        """Bind the live-sampling gauge callbacks for one SLO. Each
        callback evaluates first (amortized to once per ledger bucket —
        one scrape reading all of an SLO's series pays one evaluation,
        not one per series), so a scrape advances the alert lifecycle
        even when no traffic is arriving (firing alerts can resolve
        during a quiet incident tail)."""
        fns = []

        def budget_fn(s=s):
            self._emit(s.evaluate(), s)
            return s.budget_remaining()
        _BUDGET.set_function(budget_fn, slo=s.name)
        fns.append((_BUDGET, budget_fn))
        for w in s.windows:
            wl = "%gs" % w

            def burn_fn(s=s, w=w):
                self._emit(s.evaluate(), s)
                return s.burn_rate(w)
            _BURN.set_function(burn_fn, slo=s.name, window=wl)
            fns.append((_BURN, burn_fn))
        for p in s.pairs:
            def firing_fn(s=s, p=p):
                self._emit(s.evaluate(), s)
                return 1.0 if p.state == "firing" else 0.0
            _FIRING.set_function(firing_fn, slo=s.name, pair=p.name)
            fns.append((_FIRING, firing_fn))
        with self._lock:
            self._gauge_fns[s.name] = fns

    # ----------------------------------------------------------- observation
    def observe(self, model, code, latency_ms=None, now=None):
        """Feed one terminal outcome into every SLO of ``model`` (seeding
        the defaults on first sight of an ELIGIBLE outcome — a model
        served without going through registry.load still gets accounted,
        but a hostile probe of a nonexistent name, whose only possible
        outcomes are 400/404, never mints an SLO). Emits flightrec
        events for any alert transitions."""
        with self._lock:
            slos = list(self._by_model.get(model, ()))
        if not slos:
            if not _eligible(code):
                return
            slos = self.ensure_model(model)
        for s in slos:
            self._emit(s.observe(code, latency_ms=latency_ms, now=now), s)

    def observe_named(self, name, code, latency_ms=None, now=None):
        """Feed one outcome into EXACTLY the named SLO (no-op when it
        does not exist). The per-tenant inter-token objectives need this
        addressing: ``observe(model, ...)`` fans one outcome into every
        SLO of the model, which would charge tenant A's token gap
        against tenant B's budget. The caller defines the objective
        first (``define``) and then feeds only its own series here."""
        with self._lock:
            s = self._slos.get(name)
        if s is None:
            return
        self._emit(s.observe(code, latency_ms=latency_ms, now=now), s)

    def _emit(self, transitions, s):
        """One flightrec event per alert state transition — the alert
        history rides the black-box tape (and the crash/stall dumps)."""
        for p, state, burn_short, burn_long in transitions:
            flightrec.record("slo_alert", slo=s.name, pair=p.name,
                             state=state, threshold=p.threshold,
                             burn_short=round(burn_short, 3),
                             burn_long=round(burn_long, 3))

    # ------------------------------------------------------------ inspection
    def get(self, name):
        with self._lock:
            return self._slos.get(name)

    def for_model(self, model):
        with self._lock:
            return list(self._by_model.get(model, ()))

    def names_for_model(self, model):
        with self._lock:
            return [s.name for s in self._by_model.get(model, ())]

    def describe(self):
        """The GET /debug/slo payload: every SLO's budget, burn rates,
        and alert states (evaluated now)."""
        with self._lock:
            slos = list(self._slos.values())
        out = []
        for s in slos:
            now = s.clock()
            self._emit(s.evaluate(now, force=True), s)
            out.append(s.describe(now, evaluate=False))
        return {"slos": out}

    # -------------------------------------------------------------- teardown
    def detach_model(self, model):
        """Forget one model's SLOs and unbind their gauge callbacks
        (batcher close / model unload): a dead model must not export a
        frozen burn rate, nor have its gauge closures pin the ledgers.
        The mxtpu_slo_events_total counters stay — process-lifetime
        cumulative by Prometheus convention."""
        with self._lock:
            slos = self._by_model.pop(model, [])
            fns = []
            for s in slos:
                self._slos.pop(s.name, None)
                fns.extend(self._gauge_fns.pop(s.name, ()))
        for metric, fn in fns:
            metric.remove_function(fn)

    def reset(self):
        """Drop every SLO + gauge binding (test isolation)."""
        with self._lock:
            models = list(self._by_model)
        for m in models:
            self.detach_model(m)


#: The process-wide registry the serving path feeds (the only publisher
#: of the mxtpu_slo_* gauges).
REGISTRY = SLORegistry(publish=True)


def observe(model, code, latency_ms=None):
    REGISTRY.observe(model, code, latency_ms=latency_ms)


def ensure_model(model):
    return REGISTRY.ensure_model(model)


def describe():
    return REGISTRY.describe()
