"""Deterministic fault injection for the serving stack (faultlab).

Named fault points are compiled into the hot paths as near-zero-cost
no-ops: a site guards its hook with ``if faultlab.armed:`` — one module
attribute read when the lab is disarmed, nothing else. Arming installs a
set of faults parsed from a spec string (``MXTPU_FAULTLAB`` at import, or
``POST /debug/faults`` at runtime), and from then on ``fire(site)``
consults the armed set under a lock.

Spec grammar (docs/RESILIENCE.md "Fault spec grammar")::

    spec    := entry (";" entry)*
    entry   := site ":" kind (":" key "=" value)*
    kind    := exception | replica_kill | slow_ms | kv_oom
             | nan_poison | artifact_corrupt
    key     := stride | p | seed | budget | ms

``stride=N`` fires on every Nth call of the site (deterministic; the
default is stride=1, i.e. every call). ``p=0.3`` fires each call with
probability 0.3 from a seeded ``random.Random`` (``seed=N``; the default
seed is derived from the site+kind string, so two processes arming the
same spec fire identically). ``budget=N`` caps total firings — an
exhausted fault disarms itself. ``ms=N`` is the sleep for ``slow_ms``.

What a firing does depends on the kind:

- ``exception``    -> raises :class:`FaultInjected` (a RuntimeError —
  absorbed by the same guards that absorb real servable failures),
- ``replica_kill`` -> raises :class:`WorkerKilled` (a **BaseException**,
  so it escapes per-batch ``except Exception`` guards and kills the
  worker thread the way a segfaulting dispatch would),
- ``slow_ms``      -> sleeps ``ms`` milliseconds in place,
- ``kv_oom``       -> raises :class:`KVOomInjected`,
- ``nan_poison`` / ``artifact_corrupt`` -> returns the kind string; the
  SITE applies the corruption itself (poisons its output tensor, treats
  the artifact as unreadable) because only the site knows its data.

Every firing lands in the flight recorder (``fault_injected``) and on
``mxtpu_faults_injected_total{site,kind}`` — a chaos run's injected
faults are first-class telemetry, auditable next to their effects.

Known sites (the registry is open — any string names a site, these are
the ones wired today): ``batcher.dispatch``, ``registry.load``,
``aot.artifact_read``, ``generate.step``, ``numwatch.shadow``.
"""
from __future__ import annotations

import logging
import random
import threading
import time
import zlib

from .registry import counter
from . import flightrec

__all__ = ["FaultInjected", "WorkerKilled", "KVOomInjected", "KINDS",
           "arm", "disarm", "describe", "fire", "reset", "armed"]

_LOG = logging.getLogger(__name__)

KINDS = ("exception", "replica_kill", "slow_ms", "kv_oom", "nan_poison",
         "artifact_corrupt")

#: Kinds fire() RETURNS (site applies the corruption) instead of raising.
_PASSIVE_KINDS = ("nan_poison", "artifact_corrupt")

_FIRED = counter(
    "mxtpu_faults_injected_total",
    "Faultlab firings by site and kind (chaos-run audit trail).",
    ("site", "kind"))

#: Module-level fast path: hot sites guard with ``if faultlab.armed:`` so
#: a disarmed lab costs one attribute read on the dispatch path.
armed = False

_lock = threading.RLock()
_faults = {}                     # site -> [_Fault, ...]


class FaultInjected(RuntimeError):
    """Injected servable-level failure (absorbed like a real one)."""


class WorkerKilled(BaseException):
    """Injected worker death: a BaseException on purpose, so it escapes
    per-batch ``except Exception`` guards and takes the worker thread
    down the way a hard crash would."""


class KVOomInjected(RuntimeError):
    """Injected KV-cache allocation failure (decode-loop site)."""


class _Fault:
    """One armed fault: site + kind + firing policy + budget."""

    __slots__ = ("site", "kind", "stride", "p", "seed", "budget", "ms",
                 "calls", "fired", "_rng")

    def __init__(self, site, kind, stride=None, p=None, seed=None,
                 budget=None, ms=50.0):
        if kind not in KINDS:
            raise ValueError("unknown fault kind %r (site %r); kinds: %s"
                             % (kind, site, ", ".join(KINDS)))
        if stride is not None and p is not None:
            raise ValueError("fault %s:%s: stride= and p= are exclusive"
                             % (site, kind))
        self.site = site
        self.kind = kind
        self.stride = int(stride) if stride is not None else None
        self.p = float(p) if p is not None else None
        # default seed derived from the site+kind STRING (not hash(),
        # which is per-process randomized): same spec -> same firings
        # in every process, which is what makes a chaos run replayable
        self.seed = (int(seed) if seed is not None
                     else zlib.crc32(("%s:%s" % (site, kind)).encode()))
        self.budget = int(budget) if budget is not None else None
        self.ms = float(ms)
        self.calls = 0
        self.fired = 0
        self._rng = random.Random(self.seed)

    def should_fire(self):
        """Advance the call counter and decide (caller holds _lock)."""
        if self.budget is not None and self.fired >= self.budget:
            return False
        self.calls += 1
        if self.p is not None:
            fire = self._rng.random() < self.p
        else:
            stride = self.stride or 1
            fire = self.calls % stride == 0
        if fire:
            self.fired += 1
        return fire

    def exhausted(self):
        return self.budget is not None and self.fired >= self.budget

    def describe(self):
        return {"site": self.site, "kind": self.kind, "stride": self.stride,
                "p": self.p, "seed": self.seed, "budget": self.budget,
                "ms": self.ms, "calls": self.calls, "fired": self.fired}


def parse_spec(spec):
    """Parse a spec string into a list of _Fault (raises ValueError on a
    malformed entry — an armed typo must fail loudly, not silently test
    nothing)."""
    faults = []
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                "fault entry %r: want site:kind[:key=value...]" % entry)
        site, kind = parts[0].strip(), parts[1].strip()
        kwargs = {}
        for kv in parts[2:]:
            if "=" not in kv:
                raise ValueError("fault entry %r: bad option %r (want "
                                 "key=value)" % (entry, kv))
            k, v = kv.split("=", 1)
            k = k.strip()
            if k not in ("stride", "p", "seed", "budget", "ms"):
                raise ValueError("fault entry %r: unknown key %r" % (entry, k))
            kwargs[k] = v.strip()
        faults.append(_Fault(site, kind, **kwargs))
    return faults


def arm(spec):
    """Replace the armed fault set with the parsed ``spec`` (empty/None
    disarms everything). Returns describe()."""
    global armed
    faults = parse_spec(spec)
    with _lock:
        _faults.clear()
        for f in faults:
            _faults.setdefault(f.site, []).append(f)
        armed = bool(_faults)
        for f in faults:
            flightrec.record("fault_armed", site=f.site, kind=f.kind,
                             stride=f.stride, p=f.p, budget=f.budget)
    return describe()


def disarm():
    """Remove every armed fault (the ``POST /debug/faults`` empty-spec
    path and the test teardown path)."""
    return arm("")


def reset():
    """Test hook: disarm and forget all firing counters."""
    disarm()


def describe():
    """{armed, faults: [...]} — the ``GET /debug/faults`` body."""
    with _lock:
        return {"armed": armed,
                "faults": [f.describe()
                           for fl in _faults.values() for f in fl]}


def fire(site, **ctx):
    """Evaluate the armed faults for ``site``. Hot paths call this only
    behind the ``armed`` fast-path check.

    Raises for the raising kinds (FaultInjected / WorkerKilled /
    KVOomInjected), sleeps in place for slow_ms, and RETURNS the kind
    string for the passive kinds (nan_poison / artifact_corrupt) so the
    site can apply its own corruption; returns None when nothing fires.
    ``ctx`` keyword facts (model, replica, ...) ride onto the flightrec
    row."""
    global armed
    to_fire = []
    with _lock:
        for f in _faults.get(site, ()):
            if f.should_fire():
                to_fire.append(f)
        # budget-exhausted faults self-disarm; recompute the fast path
        for fl in list(_faults.values()):
            fl[:] = [f for f in fl if not f.exhausted()]
        for s in [s for s, fl in _faults.items() if not fl]:
            del _faults[s]
        armed = bool(_faults)
    passive = None
    for f in to_fire:
        try:
            _FIRED.inc(site=site, kind=f.kind)
        except Exception:
            _LOG.debug("fault firing counter update failed", exc_info=True)
        flightrec.record("fault_injected", site=site, kind=f.kind,
                         fired=f.fired, **ctx)
        if f.kind == "exception":
            raise FaultInjected("faultlab: injected exception at %r" % site)
        if f.kind == "replica_kill":
            raise WorkerKilled("faultlab: injected worker kill at %r" % site)
        if f.kind == "kv_oom":
            raise KVOomInjected("faultlab: injected KV OOM at %r" % site)
        if f.kind == "slow_ms":
            time.sleep(f.ms / 1000.0)
        else:                        # nan_poison / artifact_corrupt
            passive = f.kind
    return passive


def _arm_from_env():
    """Import-time arming from MXTPU_FAULTLAB (guarded: faultlab must
    never take down an import chain, and config may not be importable yet
    in exotic bootstrap orders)."""
    try:
        from .. import config
        spec = config.get_env("MXTPU_FAULTLAB")
    except Exception:
        _LOG.debug("MXTPU_FAULTLAB read failed at import", exc_info=True)
        return
    if spec:
        try:
            arm(spec)
        except Exception:
            _LOG.error("MXTPU_FAULTLAB spec %r failed to arm", spec,
                       exc_info=True)


_arm_from_env()
