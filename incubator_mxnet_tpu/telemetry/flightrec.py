"""Flight recorder: a bounded, lock-cheap ring buffer of structured
events — *what was the process doing just before it stalled or died*.

Hot paths append one small dict per coarse phase transition (step
begin/end, compile start/end, batcher dispatch, kvstore push/pull, io
waits). The ring is a ``deque(maxlen=MXTPU_FLIGHTREC_SIZE)``: appends are
GIL-atomic (no lock on the hot path), memory is bounded, and the oldest
events age out — the black-box recorder model. Readers copy the ring with
a bounded retry instead of locking writers out.

Three ways the tape leaves the process:

- **on demand** — ``snapshot()``/``tail(n)``/``dump(path)`` (JSONL), and
  the serving front-end's ``GET /debug/flightrec``;
- **on unhandled exceptions** — ``install_crash_dump()`` (wired at
  package import) chains ``sys.excepthook`` and ``threading.excepthook``
  so a crashing main thread OR a dying worker writes the tail to
  ``MXTPU_FLIGHTREC_FILE`` before the stack trace scrolls by (gate:
  ``MXTPU_FLIGHTREC_DUMP_ON_CRASH``);
- **on stalls** — the watchdog appends the tail to its stall report
  (telemetry/watchdog.py).

``record()`` is safe before/without configuration and never raises into
the instrumented path.
"""
from __future__ import annotations

import itertools
import json
import sys
import threading
import time

from .ringbuf import BoundedRing

__all__ = ["record", "snapshot", "tail", "format_tail", "dump",
           "event_mono_us", "install_crash_dump", "reset"]

_seq = itertools.count(1)
#: the tape (shared machinery with the span ring)
_ring = BoundedRing("MXTPU_FLIGHTREC_SIZE", min_size=16)
_hooks_installed = False
_dump_lock = threading.Lock()    # one crash dump at a time


def _now_us():
    from .. import profiler
    return profiler.now_us()


def record(event, **fields):
    """Append one event (``event`` kind + small JSON-able fields; the
    reserved keys seq/ts_us/mono_us/event/thread are set here). Events
    carry BOTH clocks: ``ts_us`` is the epoch-anchored profiler clock
    (human-readable, joins chrome traces), ``mono_us`` is the raw
    ``perf_counter`` — the NTP-step-immune anchor the metric-history
    incident builder (telemetry/history.py) orders timelines on. Old
    dumps without mono_us still parse (readers fall back to ts_us).
    Never raises into the caller — the recorder must not be able to
    fail the path it observes."""
    try:
        ev = {"seq": next(_seq), "ts_us": _now_us(),
              "mono_us": time.perf_counter() * 1e6, "event": event,
              "thread": threading.current_thread().name}
        if fields:
            ev.update(fields)
        _ring.append(ev)
    except Exception:
        pass


def event_mono_us(ev):
    """The perf_counter anchor of one recorded event, falling back to
    ts_us for pre-dual-clock dumps (the two clocks differ by a constant
    within one process, so ordering is preserved either way)."""
    v = ev.get("mono_us")
    return float(v) if v is not None else float(ev.get("ts_us", 0.0))


def snapshot():
    """Current ring contents, oldest first; readers never block writers."""
    return _ring.snapshot()


def tail(n=200):
    """The newest ``n`` events, oldest first."""
    return snapshot()[-int(n):]


def format_tail(n=200):
    """The tail as JSONL text — what the watchdog embeds in a stall report
    and ``GET /debug/flightrec`` serves."""
    return "".join(json.dumps(ev, default=str) + "\n" for ev in tail(n))


def dump(path=None):
    """Write the full ring to ``path`` (default MXTPU_FLIGHTREC_FILE) as
    JSONL; returns the path."""
    if path is None:
        from .. import config
        path = config.get_env("MXTPU_FLIGHTREC_FILE")
    with open(path, "w") as f:
        for ev in snapshot():
            f.write(json.dumps(ev, default=str) + "\n")
    return path


def _crash_dump(origin, exc_type):
    """Best-effort tape dump on an unhandled exception; once per process
    unless the first attempt failed. Returns the path or None."""
    from .. import config
    try:
        if not config.get_env("MXTPU_FLIGHTREC_DUMP_ON_CRASH"):
            return None
        if not len(_ring):
            return None           # nothing recorded: nothing worth a file
        with _dump_lock:
            record("crash", origin=origin, exc=exc_type.__name__)
            path = dump()
        sys.stderr.write("[mxtpu] flight recorder dumped to %s (%s in %s)\n"
                         % (path, exc_type.__name__, origin))
        return path
    except Exception:
        return None               # the crash handler must never crash


def install_crash_dump():
    """Chain the flight-recorder dump onto ``sys.excepthook`` and
    ``threading.excepthook`` (both: a serving worker dies via the
    threading hook, a training script via the sys one). Idempotent; the
    previous hooks still run, so tracebacks print exactly as before."""
    global _hooks_installed
    if _hooks_installed:
        return
    _hooks_installed = True

    prev_sys = sys.excepthook
    prev_threading = threading.excepthook

    def sys_hook(exc_type, exc, tb):
        _crash_dump("main", exc_type)
        prev_sys(exc_type, exc, tb)

    def threading_hook(args):
        if args.exc_type is not SystemExit:
            _crash_dump(getattr(args.thread, "name", "thread"),
                        args.exc_type)
        prev_threading(args)

    sys.excepthook = sys_hook
    threading.excepthook = threading_hook


def reset():
    """Drop the tape and re-read MXTPU_FLIGHTREC_SIZE (test isolation)."""
    _ring.reset()
