"""Metric history: a bounded in-process time-series store — *what did the
metrics look like in the 90 seconds before the page*.

Every existing signal is instantaneous (a scrape, an EMA gauge, an event
ring with no metric context). This module closes the postmortem gap: a
daemon thread self-scrapes the process-wide telemetry registry every
``MXTPU_HISTORY_INTERVAL_S`` through ``REGISTRY.samples()`` (the
registry-iteration API — no exposition-text round trip) into per-series
fixed-size rings with tiered downsampling:

- **raw ring** — the newest ``MXTPU_HISTORY_RAW`` (t, value) points;
- **coarse ring** — every ``MXTPU_HISTORY_COARSE_EVERY`` raw samples fold
  into one {t, min, max, mean} point, ``MXTPU_HISTORY_COARSE`` kept — so
  retention covers RAW*interval of full-resolution history plus
  COARSE*COARSE_EVERY*interval of summarized history, in constant memory.

Recording rules run at sample time, not query time:

- ``rate(<counter>)``       — per-second increase of every counter since
  the previous tick (scrape-gap-exact, clamped at resets);
- ``slope(<gauge>)``        — least-squares trend of queue-depth and SLO
  burn-rate gauges over ``MXTPU_HISTORY_SLOPE_WINDOW_S`` (the
  burn-rate *trajectory*: is the budget spend accelerating?);
- ``mxtpu_history_window_mfu`` — window MFU from devstats dispatch-total
  deltas between ticks (delta flops / delta chip-seconds / peak), the
  honest utilization-over-time series the cumulative gauges cannot give.

A trend detector turns the derived series into hysteresis-gated flightrec
early warnings — one event per episode, not per tick:

- ``pressure_rising``  — a model's queue-depth trend line predicts
  crossing its capacity (mxtpu_serving_queue_capacity, else
  ``MXTPU_HISTORY_PRESSURE_DEPTH``) within
  ``MXTPU_HISTORY_PRESSURE_HORIZON_S``; closes when the prediction
  retreats past twice the horizon or the slope turns non-positive.
- ``mfu_droop`` — window MFU falls below ``MXTPU_HISTORY_DROOP_FRAC`` of
  its trailing ``MXTPU_HISTORY_DROOP_WINDOW_S`` median; closes at
  halfway between the droop line and the median (re-arm hysteresis).

Consumption: ``GET /debug/history?series=&since=&step=`` (query()),
``GET /debug/incident?around=<ts>`` (incident() — flightrec events, SLO
alert transitions and metric excursions merged into one causally-ordered
timeline on the shared perf_counter anchor), JSONL export to
``MXTPU_HISTORY_FILE`` (atomic tmp+rename rotation; tools/tsq.py reads
it offline), and the loadgen between-stage ``history`` block.

Lifecycle mirrors the watchdog: ``start()``/``stop()``/``running()``,
``MXTPU_HISTORY=1`` autostarts at package import, and batcher close calls
``detach_model(name)`` so an unloaded model's series and episode state do
not outlive it. Samples are timestamped with BOTH clocks (epoch-anchored
``profiler.now_us`` and raw ``perf_counter``) so they join flightrec's
dual-clock events exactly.
"""
from __future__ import annotations

import collections
import json
import logging
import math
import os
import re
import threading
import time

from . import flightrec
from .registry import REGISTRY, counter

__all__ = ["sample_once", "query", "stats", "series_names", "incident",
           "export_jsonl", "detach_model", "start", "stop", "running",
           "describe", "reset"]

_LOG = logging.getLogger(__name__)

#: gauges whose trend (least-squares slope) is a recording rule — queue
#: depths feed the pressure detector, burn rates give the SLO trajectory
SLOPE_RULES = ("mxtpu_serving_queue_depth", "mxtpu_slo_burn_rate")

#: metric prefixes the history store does NOT retain: its own bookkeeping
#: (self-reference would grow series per restart) — everything else the
#: registry exports is fair game for the postmortem.
_SKIP_PREFIXES = ("mxtpu_history_store_",)

_TICKS = counter(
    "mxtpu_history_store_ticks_total",
    "Self-scrape ticks the metric-history daemon completed.")
_DROPPED = counter(
    "mxtpu_history_store_dropped_series_total",
    "Samples dropped because the store was at MXTPU_HISTORY_MAX_SERIES "
    "distinct series (new series only; established series keep "
    "recording).")
_WARNINGS = counter(
    "mxtpu_history_early_warnings_total",
    "Trend-detector episodes opened, by kind (pressure_rising, "
    "mfu_droop) — one per episode, not per tick.", ("kind",))


def _cfg(name):
    from .. import config
    return config.get_env(name)


def _now_s():
    from .. import profiler
    return profiler.now_us() / 1e6


class _Series:
    """One series' tiered rings + fold accumulator. All mutation happens
    under the store lock (the sampler is single-threaded; queries and
    exports take the same lock for a consistent copy)."""

    __slots__ = ("raw", "coarse", "_acc", "_acc_n")

    def __init__(self, raw_cap, coarse_cap):
        self.raw = collections.deque(maxlen=raw_cap)     # (t, value)
        self.coarse = collections.deque(maxlen=coarse_cap)  # (t,min,max,mean)
        self._acc = None                 # [t0, min, max, sum, n] folding
        self._acc_n = 0

    def add(self, t, v, fold_every):
        self.raw.append((t, v))
        if self._acc is None:
            self._acc = [t, v, v, 0.0, 0]
        a = self._acc
        a[1] = min(a[1], v)
        a[2] = max(a[2], v)
        a[3] += v
        a[4] += 1
        if a[4] >= fold_every:
            # the coarse point is stamped at the fold's LAST raw t: the
            # summary describes the window ENDING there
            self.coarse.append((t, a[1], a[2], a[3] / a[4]))
            self._acc = None


class _Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._series = {}          # series id -> _Series
        self._prev_counters = {}   # series id -> (t, value) for rate()
        self._prev_devstats = None  # (t, flops, chip_s)
        self._episodes = {}        # (kind, key) -> True while open
        self._last_mono = None     # perf_counter of the newest tick
        self._last_epoch = None

    def reset(self):
        with self._lock:
            self._series.clear()
            self._prev_counters.clear()
            self._prev_devstats = None
            self._episodes.clear()
            self._last_mono = self._last_epoch = None


_STORE = _Store()

_state_lock = threading.Lock()   # daemon lifecycle only
_thread = None
_stop_event = None


# ------------------------------------------------------------ series ids
def _series_id(name, labels):
    """Prometheus-style identity: ``name{label="v",...}`` (labels in the
    metric's declared order, the same rendering exposition uses) — the
    key series are queried, exported, and diffed by."""
    if not labels:
        return name
    return "%s{%s}" % (name, ",".join('%s="%s"' % (k, v)
                                      for k, v in labels.items()))


_MODEL_LABEL_RE = re.compile(r'model="([^"]*)"')


def _series_model(sid):
    m = _MODEL_LABEL_RE.search(sid)
    return m.group(1) if m else None


# ------------------------------------------------------------- sampling
def _put(t, sid, value, raw_cap, coarse_cap, fold_every, max_series):
    """Record one sample under the store lock; new series past the cap
    are dropped (counted), established series always record."""
    s = _STORE._series.get(sid)
    if s is None:
        if len(_STORE._series) >= max_series:
            _DROPPED.inc()
            return
        s = _STORE._series[sid] = _Series(raw_cap, coarse_cap)
    s.add(t, float(value), fold_every)


def _linfit_slope(points):
    """Least-squares slope (value units per second) of [(t, v)] — the
    queue-depth / burn-rate trend rule. None for degenerate windows."""
    n = len(points)
    if n < 3:
        return None
    mt = sum(p[0] for p in points) / n
    mv = sum(p[1] for p in points) / n
    den = sum((p[0] - mt) ** 2 for p in points)
    if den <= 0.0:
        return None
    return sum((p[0] - mt) * (p[1] - mv) for p in points) / den


def _window_mfu(t):
    """Window MFU from devstats dispatch-total deltas between ticks —
    None when devstats is idle (no dispatches this window)."""
    try:
        from . import devstats
        tot = devstats.dispatch_totals()
        peak = devstats.peaks()[0]
    except Exception:
        return None
    cur = (t, float(tot["flops"]), float(tot["chip_s"]))
    prev, _STORE._prev_devstats = _STORE._prev_devstats, cur
    if prev is None:
        return None
    d_flops, d_chip = cur[1] - prev[1], cur[2] - prev[2]
    if d_chip <= 0.0 or peak <= 0.0:
        return None
    return max(0.0, d_flops / d_chip / peak)


def _trailing(sid, t, window_s):
    ser = _STORE._series.get(sid)
    if ser is None:
        return []
    lo = t - window_s
    return [p for p in ser.raw if p[0] >= lo]


def _episode(kind, key, open_now, fields):
    """Hysteresis bookkeeping: flightrec-record the OPEN transition once
    per episode; silently close. Returns True while the episode is open."""
    ek = (kind, key)
    was = _STORE._episodes.get(ek, False)
    if open_now and not was:
        _STORE._episodes[ek] = True
        flightrec.record(kind, **fields)
        _WARNINGS.inc(kind=kind)
    elif not open_now and was:
        _STORE._episodes.pop(ek, None)
    return open_now


def _detect_pressure(t, depths, capacities, horizon_s, fallback_depth,
                     slope_window_s):
    """pressure_rising per model: the depth trend line predicts crossing
    capacity within the horizon. Open: predicted time-to-saturation <=
    horizon. Close: slope <= 0 or prediction retreats past 2x horizon
    (hysteresis — a prediction hovering at the boundary must not flap)."""
    for model, depth in depths.items():
        sid = _series_id("mxtpu_serving_queue_depth", {"model": model})
        slope = _linfit_slope(_trailing(sid, t, slope_window_s))
        cap = capacities.get(model, fallback_depth)
        ek = ("pressure_rising", model)
        if slope is None or cap is None or cap <= 0.0:
            _STORE._episodes.pop(ek, None)
            continue
        _put(t, "slope(%s)" % sid, slope, *_caps())
        if slope <= 0.0 or depth >= cap:
            # falling (or already saturated — that is shedding territory,
            # not an early warning): close
            _STORE._episodes.pop(ek, None)
            continue
        eta_s = (cap - depth) / slope
        was_open = _STORE._episodes.get(ek, False)
        open_now = eta_s <= (horizon_s if not was_open else 2.0 * horizon_s)
        _episode("pressure_rising", model, open_now,
                 {"model": model, "queue_depth": depth, "capacity": cap,
                  "slope_per_s": slope, "eta_s": eta_s,
                  "horizon_s": horizon_s})


def _detect_droop(t, mfu, droop_frac, droop_window_s):
    """mfu_droop: window MFU below droop_frac of its trailing median.
    Close threshold is halfway between the droop line and the median —
    MFU must genuinely recover before the detector re-arms."""
    sid = "mxtpu_history_window_mfu"
    pts = _trailing(sid, t, droop_window_s)
    ek = ("mfu_droop", "-")
    if mfu is None or len(pts) < 6:
        _STORE._episodes.pop(ek, None)
        return
    vals = sorted(v for _, v in pts)
    med = vals[len(vals) // 2]
    if med <= 0.0:
        _STORE._episodes.pop(ek, None)
        return
    open_thr = droop_frac * med
    close_thr = (open_thr + med) / 2.0
    was_open = _STORE._episodes.get(ek, False)
    open_now = mfu < (close_thr if was_open else open_thr)
    _episode("mfu_droop", "-", open_now,
             {"window_mfu": mfu, "median_mfu": med, "droop_frac": droop_frac,
              "window_s": droop_window_s})


def _caps():
    return (max(2, int(_cfg("MXTPU_HISTORY_RAW"))),
            max(2, int(_cfg("MXTPU_HISTORY_COARSE"))),
            max(1, int(_cfg("MXTPU_HISTORY_COARSE_EVERY"))),
            max(1, int(_cfg("MXTPU_HISTORY_MAX_SERIES"))))


def sample_once(now_s=None):
    """One self-scrape tick: walk REGISTRY.samples(), evaluate the
    recording rules against the previous tick, run the trend detector,
    export when MXTPU_HISTORY_FILE is set. The daemon calls this on its
    interval; tests and the CI stage call it directly for deterministic
    timelines. Returns the number of samples stored this tick."""
    t = _now_s() if now_s is None else float(now_s)
    raw_cap, coarse_cap, fold_every, max_series = _caps()
    try:
        scraped = REGISTRY.samples()
    except Exception:
        _LOG.debug("history scrape failed", exc_info=True)
        return 0
    stored = 0
    depths, capacities = {}, {}
    with _STORE._lock:
        _STORE._last_mono = time.perf_counter()
        _STORE._last_epoch = t
        for name, kind, labels, value in scraped:
            if name.startswith(_SKIP_PREFIXES):
                continue
            sid = _series_id(name, labels)
            _put(t, sid, value, raw_cap, coarse_cap, fold_every,
                 max_series)
            stored += 1
            if kind == "counter" or name.endswith(("_sum", "_count")):
                # rate() rule: per-second increase since the previous
                # tick; a reset (restarted counter) clamps to 0, never a
                # negative rate
                prev = _STORE._prev_counters.get(sid)
                _STORE._prev_counters[sid] = (t, value)
                if prev is not None and t > prev[0]:
                    rate = max(0.0, (value - prev[1]) / (t - prev[0]))
                    _put(t, "rate(%s)" % sid, rate, raw_cap, coarse_cap,
                         fold_every, max_series)
            elif name == "mxtpu_serving_queue_depth":
                depths[labels.get("model", "-")] = value
            elif name == "mxtpu_serving_queue_capacity":
                capacities[labels.get("model", "-")] = value
            elif name == "mxtpu_slo_burn_rate":
                slope = _linfit_slope(_trailing(sid, t, float(
                    _cfg("MXTPU_HISTORY_SLOPE_WINDOW_S"))))
                if slope is not None:
                    _put(t, "slope(%s)" % sid, slope, raw_cap,
                         coarse_cap, fold_every, max_series)
        mfu = _window_mfu(t)
        if mfu is not None:
            _put(t, "mxtpu_history_window_mfu", mfu, raw_cap, coarse_cap,
                 fold_every, max_series)
        try:
            _detect_pressure(
                t, depths, capacities,
                float(_cfg("MXTPU_HISTORY_PRESSURE_HORIZON_S")),
                _cfg("MXTPU_HISTORY_PRESSURE_DEPTH"),
                float(_cfg("MXTPU_HISTORY_SLOPE_WINDOW_S")))
            _detect_droop(t, mfu, float(_cfg("MXTPU_HISTORY_DROOP_FRAC")),
                          float(_cfg("MXTPU_HISTORY_DROOP_WINDOW_S")))
        except Exception:
            _LOG.debug("history trend detection failed", exc_info=True)
    _TICKS.inc()
    path = _cfg("MXTPU_HISTORY_FILE")
    if path:
        try:
            export_jsonl(path)
        except Exception:
            _LOG.debug("history export to %r failed", path, exc_info=True)
    return stored


# -------------------------------------------------------------- querying
def series_names():
    """Sorted ids of every retained series (scraped and derived)."""
    with _STORE._lock:
        return sorted(_STORE._series)


def _downsample(points, step):
    """Raw (t, v) points folded into step-aligned {t, min, max, mean}
    buckets (t = bucket END) — the ?step= query shape, same summary
    statistics as the coarse ring."""
    out = []
    cur_end, mn, mx, sm, n = None, 0.0, 0.0, 0.0, 0
    for t, v in points:
        end = (math.floor(t / step) + 1) * step
        if cur_end is None or end != cur_end:
            if n:
                out.append({"t": cur_end, "min": mn, "max": mx,
                            "mean": sm / n})
            cur_end, mn, mx, sm, n = end, v, v, 0.0, 0
        mn, mx = min(mn, v), max(mx, v)
        sm += v
        n += 1
    if n:
        out.append({"t": cur_end, "min": mn, "max": mx, "mean": sm / n})
    return out


def query(series=None, since=None, step=None):
    """The /debug/history payload. ``series``: exact id, bare metric name
    (matches every label set), or substring; ``since``: epoch seconds
    (drop older points); ``step``: fold raw points into step-second
    min/max/mean buckets instead of returning them verbatim. The coarse
    ring rides along untouched — it is the long-horizon context."""
    with _STORE._lock:
        ids = sorted(_STORE._series)
        if series:
            ids = [sid for sid in ids
                   if sid == series or series in sid
                   or sid.split("{", 1)[0] == series]
        picked = {sid: (list(_STORE._series[sid].raw),
                        list(_STORE._series[sid].coarse)) for sid in ids}
        out = {"now": _STORE._last_epoch, "interval_s":
               float(_cfg("MXTPU_HISTORY_INTERVAL_S")), "series": {}}
    for sid, (raw, coarse) in picked.items():
        if since is not None:
            raw = [p for p in raw if p[0] >= since]
            coarse = [p for p in coarse if p[0] >= since]
        entry = {"coarse": [{"t": t, "min": mn, "max": mx, "mean": mean}
                            for t, mn, mx, mean in coarse]}
        if step:
            entry["raw"] = _downsample(raw, float(step))
        else:
            entry["raw"] = [[t, v] for t, v in raw]
        out["series"][sid] = entry
    return out


def stats(series, since=None):
    """(min, max, mean, n) over one series' retained raw points — the
    cheap reduction the loadgen between-stage history block reports."""
    with _STORE._lock:
        ser = _STORE._series.get(series)
        pts = list(ser.raw) if ser is not None else []
    if since is not None:
        pts = [p for p in pts if p[0] >= since]
    if not pts:
        return None
    vals = [v for _, v in pts]
    return (min(vals), max(vals), sum(vals) / len(vals), len(vals))


# ------------------------------------------------------------- incidents
#: series whose excursions an incident report hunts for — the saturation
#: and health signals a postmortem reads first.
_EXCURSION_SERIES = ("mxtpu_serving_queue_depth",
                     "mxtpu_serving_replica_queue_depth",
                     "mxtpu_http_inflight_requests",
                     "mxtpu_history_window_mfu",
                     "mxtpu_slo_burn_rate")


def _excursions(win_lo, win_hi):
    """Metric excursions inside [win_lo, win_hi]: for each watched series,
    the in-window extreme that escapes the out-of-window envelope (the
    series' own quiet baseline). Returns timeline entries stamped at the
    extreme's sample time."""
    with _STORE._lock:
        picked = {sid: list(ser.raw)
                  for sid, ser in _STORE._series.items()
                  if sid.split("{", 1)[0] in _EXCURSION_SERIES}
    out = []
    for sid, pts in sorted(picked.items()):
        inside = [p for p in pts if win_lo <= p[0] <= win_hi]
        outside = [v for t, v in pts if t < win_lo or t > win_hi]
        if not inside:
            continue
        hi_t, hi_v = max(inside, key=lambda p: p[1])
        lo_t, lo_v = min(inside, key=lambda p: p[1])
        if outside:
            base_hi, base_lo = max(outside), min(outside)
            spread = max(base_hi - base_lo, 1e-9)
        else:
            # no baseline: only a genuinely moving series is reportable
            base_hi, base_lo = hi_v, lo_v
            spread = max(hi_v - lo_v, 1e-9)
            if hi_v == lo_v:
                continue
        if hi_v > base_hi + 0.5 * spread or (not outside and hi_v > lo_v):
            out.append({"t": hi_t, "type": "excursion", "series": sid,
                        "direction": "high", "value": hi_v,
                        "baseline_max": base_hi, "baseline_min": base_lo})
        if outside and lo_v < base_lo - 0.5 * spread:
            out.append({"t": lo_t, "type": "excursion", "series": sid,
                        "direction": "low", "value": lo_v,
                        "baseline_max": base_hi, "baseline_min": base_lo})
    return out


def incident(around=None, before_s=90.0, after_s=30.0):
    """The /debug/incident payload: one causally-ordered timeline of
    flightrec events (fault injections, respawns, early warnings), SLO
    alert transitions (the slo_alert events the SLO engine records), and
    the metric excursions bracketing them, for the window
    ``[around-before_s, around+after_s]``. ``around`` is epoch seconds
    (profiler.now_us()/1e6 domain), default now. Ordering is on the
    shared perf_counter anchor (events' mono_us, converted via this
    process's constant epoch-mono offset), so an NTP step between event
    and scrape cannot reorder the story."""
    t_now = _now_s()
    around = t_now if around is None else float(around)
    win_lo, win_hi = around - float(before_s), around + float(after_s)
    # this process's constant offset between the epoch-anchored clock and
    # raw perf_counter: lets event mono_us sort on the same axis as the
    # epoch-stamped samples
    off = t_now - time.perf_counter()
    entries = []
    for ev in flightrec.snapshot():
        t = flightrec.event_mono_us(ev) / 1e6
        if "mono_us" in ev:
            t += off
        if not (win_lo <= t <= win_hi):
            continue
        kind = "alert" if ev.get("event") == "slo_alert" else "event"
        e = {"t": t, "type": kind}
        e.update({k: v for k, v in ev.items() if k != "mono_us"})
        entries.append(e)
    entries.extend(_excursions(win_lo, win_hi))
    entries.sort(key=lambda e: (e["t"], e.get("seq", 0)))
    return {"around": around, "window": [win_lo, win_hi],
            "timeline": entries}


# --------------------------------------------------------------- export
def _canon(obj):
    """The one serialization tsq must byte-match on round-trip."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def export_jsonl(path=None):
    """Write the full store as JSONL — one meta line, then one line per
    series, sorted — atomically (tmp + rename, the flush_to_file
    discipline): a concurrent tsq read never sees a torn file. The
    serialization is canonical (sorted keys, no whitespace) so tsq can
    round-trip it byte-stable. Returns the path."""
    if path is None:
        path = _cfg("MXTPU_HISTORY_FILE")
    if not path:
        raise ValueError("no path given and MXTPU_HISTORY_FILE unset")
    with _STORE._lock:
        meta = {"schema": "mxtpu-history-v1",
                "interval_s": float(_cfg("MXTPU_HISTORY_INTERVAL_S")),
                "now": _STORE._last_epoch}
        rows = [{"series": sid,
                 "raw": [[t, v] for t, v in ser.raw],
                 "coarse": [[t, mn, mx, mean]
                            for t, mn, mx, mean in ser.coarse]}
                for sid, ser in sorted(_STORE._series.items())]
    tmp = "%s.%d.%d.tmp" % (path, os.getpid(), threading.get_ident())
    with open(tmp, "w") as f:
        f.write(_canon(meta) + "\n")
        for row in rows:
            f.write(_canon(row) + "\n")
    os.replace(tmp, path)
    return path


# ------------------------------------------------------------- lifecycle
def detach_model(model):
    """Drop every series labeled model=<model> (scraped AND derived) plus
    its trend-episode and rate state — batcher close calls this so an
    unloaded model's history cannot leak memory or resurface in the next
    incident report."""
    needle = 'model="%s"' % model
    with _STORE._lock:
        for sid in [s for s in _STORE._series if needle in s]:
            _STORE._series.pop(sid, None)
        for sid in [s for s in _STORE._prev_counters if needle in s]:
            _STORE._prev_counters.pop(sid, None)
        for ek in [k for k in _STORE._episodes if k[1] == model]:
            _STORE._episodes.pop(ek, None)


def describe():
    """Store shape for dashboards/tests: series count, caps, tick facts."""
    raw_cap, coarse_cap, fold_every, max_series = _caps()
    with _STORE._lock:
        n = len(_STORE._series)
        last = _STORE._last_epoch
    return {"series": n, "max_series": max_series, "raw_cap": raw_cap,
            "coarse_cap": coarse_cap, "coarse_every": fold_every,
            "interval_s": float(_cfg("MXTPU_HISTORY_INTERVAL_S")),
            "last_tick": last, "running": running()}


def _monitor(stop, interval_s):
    while not stop.wait(interval_s):
        try:
            sample_once()
        except Exception:
            # the postmortem recorder must outlive what it records — but
            # a broken tick must not be silent either (R005)
            _LOG.debug("history tick failed", exc_info=True)


def start(interval_s=None):
    """Start (or restart with new settings) the self-scrape daemon.
    Default interval: MXTPU_HISTORY_INTERVAL_S. Returns the thread."""
    global _thread, _stop_event
    if interval_s is None:
        interval_s = _cfg("MXTPU_HISTORY_INTERVAL_S")
    interval_s = max(0.01, float(interval_s))
    with _state_lock:
        _stop_locked()
        stop_ev = threading.Event()
        t = threading.Thread(target=_monitor, args=(stop_ev, interval_s),
                             daemon=True, name="mxtpu-history")
        _stop_event, _thread = stop_ev, t
        t.start()
    return t


def _stop_locked():
    global _thread, _stop_event
    stop_ev, t = _stop_event, _thread
    _stop_event = _thread = None
    if stop_ev is not None:
        stop_ev.set()
        if t is not None:
            t.join(timeout=5.0)


def stop():
    """Stop and join the daemon (R007: the daemon flag is a crash-exit
    backstop, not a lifecycle plan). The store keeps its rings — history
    outlives the sampler so a post-stop incident query still answers."""
    with _state_lock:
        _stop_locked()


def running():
    t = _thread
    return t is not None and t.is_alive()


def reset():
    """Stop the daemon and drop every ring (test isolation)."""
    stop()
    _STORE.reset()
