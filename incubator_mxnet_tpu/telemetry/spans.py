"""Hierarchical span tracing: *which phase of which step/request did the
time go to* (the causal layer on top of the registry's aggregate metrics).

A span is one named, timed region of work. Spans nest: entering a span
pushes it on a thread-local stack, so a span opened inside another becomes
its child (``parent_id`` link) with zero caller bookkeeping — the same
ambient-context discipline TensorFlow's runtime tracer uses. Each span
also carries the serving request ID (``telemetry.trace``) when one is
ambient, so one HTTP request's chain is greppable end to end.

Cross-thread / queue-boundary propagation is EXPLICIT (a thread-local
stack cannot follow a request through the batcher queue):

- ``current_context()`` captures the open span as an immutable
  ``SpanContext`` the producer attaches to the queued work item;
- the consumer either opens a live child with
  ``with span("phase", parent=ctx):`` or — when the duration was measured
  elsewhere (e.g. queue wait computed at dispatch) — emits it
  retroactively with ``record_span(name, start_us, dur_us, parent=ctx)``,
  which touches no stack at all and is therefore safe from any thread.

Every finished span lands in a bounded ring buffer (``MXTPU_SPANS_BUFFER``
records, oldest dropped) exportable as JSONL (``export_jsonl`` /
``dump_jsonl``; served at ``GET /debug/spans``), and is mirrored into the
profiler's chrome-trace stream as a complete event with
``span_id``/``parent_id``/``request_id`` args whenever the profiler is
running — one dump shows metrics-invisible causality: HTTP handler ->
queue wait -> batch dispatch -> device step.

Opt-in histogram bridge: ``set_histogram_bridge(True)`` (or
``MXTPU_SPANS_HISTOGRAM=1``) feeds every finished span's duration into the
``mxtpu_span_seconds{span=<name>}`` histogram on the shared registry —
span names are code-authored constants, a bounded label by construction.

Discipline (enforced by mxtpulint R008): a span is entered with ``with``
or, when the manual ``start()``/``end()`` API is unavoidable, inside
``try/finally`` — a span left open on an exception corrupts the ambient
parent stack for everything that thread runs next.
"""
from __future__ import annotations

import itertools
import json
import threading

from . import trace
from .ringbuf import BoundedRing

__all__ = ["Span", "SpanContext", "span", "record_span", "current_span",
           "current_context", "snapshot", "export_jsonl", "dump_jsonl",
           "set_histogram_bridge", "reset"]

# Span ids: a GIL-atomic counter (no lock, no urandom syscall per span);
# hex-rendered with a per-process random prefix so ids from two processes
# writing one trace directory cannot collide.
_ids = itertools.count(1)
_local = threading.local()

#: finished-span record ring (shared machinery with the flight recorder)
_buffer = BoundedRing("MXTPU_SPANS_BUFFER", min_size=1)

_bridge = None                   # None = follow env; True/False = forced
_SPAN_SECONDS = None             # lazily declared histogram

_PID_PREFIX = None


def _now_us():
    # profiler.now_us is the one epoch-anchored monotonic clock every
    # trace event uses; imported lazily (the package imports telemetry
    # before profiler).
    from .. import profiler
    return profiler.now_us()


def _next_id():
    global _PID_PREFIX
    if _PID_PREFIX is None:
        import os
        _PID_PREFIX = os.urandom(3).hex()
    return "%s-%x" % (_PID_PREFIX, next(_ids))


def _bridge_enabled():
    if _bridge is not None:
        return _bridge
    from .. import config
    return config.get_env("MXTPU_SPANS_HISTOGRAM")


def set_histogram_bridge(enabled=True):
    """Force the span->histogram bridge on/off (None: follow
    MXTPU_SPANS_HISTOGRAM). Opt-in because per-span observe() cost is only
    worth paying when something scrapes the histogram."""
    global _bridge
    _bridge = enabled


def _observe_bridge(rec):
    global _SPAN_SECONDS
    if _SPAN_SECONDS is None:
        from . import registry
        _SPAN_SECONDS = registry.histogram(
            "mxtpu_span_seconds",
            "Duration of finished trace spans by span name "
            "(opt-in bridge: MXTPU_SPANS_HISTOGRAM).",
            labelnames=("span",))
    _SPAN_SECONDS.observe(rec["dur_us"] / 1e6, span=rec["name"])


class SpanContext:
    """Immutable handle to a span, safe to carry across threads/queues.
    Only identity rides along — never the live Span (the owner thread
    ends it)."""

    __slots__ = ("span_id", "request_id")

    def __init__(self, span_id, request_id=None):
        self.span_id = span_id
        self.request_id = request_id

    def __repr__(self):
        return "SpanContext(%s, request_id=%s)" % (self.span_id,
                                                   self.request_id)


def _stack():
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current_span():
    """The innermost OPEN span on this thread, or None."""
    st = getattr(_local, "stack", None)
    return st[-1] if st else None


def current_context():
    """SpanContext of the innermost open span on this thread (None when no
    span is open) — the value a producer attaches to queued work."""
    sp = current_span()
    return sp.context() if sp is not None else None


class Span:
    """One named, timed region. Use ``with span(...)``; the manual
    ``start()``/``end()`` pair exists for generators/callbacks that cannot
    hold a ``with`` open and MUST be guarded by try/finally (mxtpulint
    R008)."""

    __slots__ = ("name", "span_id", "parent_id", "request_id", "args",
                 "start_us", "_open")

    def __init__(self, name, parent=None, request_id=None, **args):
        self.name = name
        self.span_id = _next_id()
        if parent is None:
            parent = current_span()
        if isinstance(parent, Span):
            self.parent_id = parent.span_id
            inherited_rid = parent.request_id
        elif isinstance(parent, SpanContext):
            self.parent_id = parent.span_id
            inherited_rid = parent.request_id
        else:
            self.parent_id = None
            inherited_rid = None
        self.request_id = (request_id if request_id is not None
                           else inherited_rid
                           if inherited_rid is not None
                           else trace.current_request_id())
        self.args = args or None
        self.start_us = None
        self._open = False

    def context(self):
        return SpanContext(self.span_id, self.request_id)

    # ------------------------------------------------------------------
    def start(self):
        self.start_us = _now_us()
        _stack().append(self)
        self._open = True
        return self

    def end(self, **extra_args):
        if not self._open:
            return
        self._open = False
        st = _stack()
        # tolerate out-of-order ends (a leaked child) without corrupting
        # everything above us: pop through to this span if present
        if self in st:
            while st and st.pop() is not self:
                pass
        if extra_args:
            self.args = dict(self.args or (), **extra_args)
        _emit(self.name, self.start_us, _now_us() - self.start_us,
              self.span_id, self.parent_id, self.request_id, self.args)

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.end(error=exc_type.__name__)
        else:
            self.end()

    def __repr__(self):
        return "Span(%r, id=%s, parent=%s)" % (self.name, self.span_id,
                                               self.parent_id)


def span(name, parent=None, request_id=None, **args):
    """Open a span: ``with span("train:step"):``. ``parent`` (a Span or a
    SpanContext carried across a queue) overrides the ambient thread-local
    parent; ``args`` land on the finished record and the chrome-trace
    event."""
    return Span(name, parent=parent, request_id=request_id, **args)


def record_span(name, start_us, dur_us, parent=None, request_id=None,
                **args):
    """Emit a finished span retroactively — no stack interaction, safe
    from any thread. This is the queue-boundary form: the dispatcher
    measures queue wait AFTER the fact and emits it as a child of the
    producer's captured SpanContext. Returns the new span's id."""
    parent_id = parent.span_id if isinstance(parent, (Span, SpanContext)) \
        else parent
    if request_id is None:
        if isinstance(parent, (Span, SpanContext)):
            request_id = parent.request_id
        if request_id is None:
            request_id = trace.current_request_id()
    span_id = _next_id()
    _emit(name, start_us, dur_us, span_id, parent_id, request_id,
          args or None)
    return span_id


def _emit(name, start_us, dur_us, span_id, parent_id, request_id, args):
    rec = {"name": name, "span_id": span_id, "parent_id": parent_id,
           "request_id": request_id, "start_us": start_us,
           "dur_us": dur_us, "thread": threading.current_thread().name}
    if args:
        rec["args"] = args
    # BoundedRing.append never raises: a misconfigured MXTPU_SPANS_BUFFER
    # drops the record, it does not crash the instrumented hot path
    _buffer.append(rec)
    # mirror into the profiler's chrome-trace stream (no-op unless the
    # profiler is running) so spans and op/batch events share one dump
    try:
        from .. import profiler
        ev_args = {"span_id": span_id}
        if parent_id is not None:
            ev_args["parent_id"] = parent_id
        if request_id is not None:
            ev_args["request_id"] = request_id
        if args:
            ev_args.update(args)
        profiler.record_event(name, "span", start_us, dur_us, args=ev_args)
    except Exception:
        pass          # tracing must never take down the traced path
    if _bridge_enabled():
        try:
            _observe_bridge(rec)
        except Exception:
            pass
    return rec


# ---------------------------------------------------------------- export
def snapshot():
    """Finished-span records, oldest first (bounded by
    MXTPU_SPANS_BUFFER); readers never block writers."""
    return _buffer.snapshot()


def export_jsonl():
    """The span buffer as JSON Lines (one span per line) — the on-demand
    export ``GET /debug/spans`` serves."""
    return "".join(json.dumps(rec, default=str) + "\n"
                   for rec in snapshot())


def dump_jsonl(path):
    """Write the span buffer to ``path`` as JSONL; returns the path."""
    with open(path, "w") as f:
        f.write(export_jsonl())
    return path


def reset():
    """Drop buffered spans and re-read MXTPU_SPANS_BUFFER (test isolation;
    open spans on other threads keep working — their records land in the
    fresh ring)."""
    _buffer.reset()
