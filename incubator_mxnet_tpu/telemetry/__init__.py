"""Unified framework telemetry (the observability surface of ROADMAP's
"serve heavy traffic as fast as the hardware allows" north star).

One process-wide registry of named counters / gauges / fixed-bucket
histograms with labels, exported in Prometheus text format — shared by the
serving stack (serving/metrics.py), the compiled training step (jit.py),
kvstore push/pull, and the data-IO pipeline (io/io.py). Request-scoped
trace IDs ride from the HTTP front-end through the batcher into the
profiler's chrome-trace events (trace.py).

Two consumption paths:

- **Scrape**: the serving server exposes ``GET /metrics`` (Prometheus
  text; the old JSON snapshot moved to ``GET /metrics.json``).
- **Headless flush**: training jobs with no HTTP server run
  ``telemetry.start_periodic_flush()`` (or set
  ``MXTPU_TELEMETRY_FLUSH_S > 0`` to autostart at import) and the
  registry is written atomically to ``MXTPU_TELEMETRY_FILE`` every
  interval — node-exporter textfile-collector compatible.

Metric naming scheme (docs/OBSERVABILITY.md): ``mxtpu_<subsystem>_<what>
[_total|_seconds|_bytes]``, labels only for BOUNDED dimensions (model
name, store type, iterator class) — never request IDs.
"""
from __future__ import annotations

import logging
import os
import threading

from .registry import (Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
                       DEFAULT_BUCKETS, OVERFLOW_LABEL, counter, gauge,
                       histogram, export_text, reset)
from .trace import (new_request_id, current_request_id,
                    set_current_request_id, request_scope,
                    REQUEST_ID_HEADER)
from . import devstats
from . import faultlab
from . import flightrec
from . import history
from . import numwatch
from . import profstats
from . import slo
from . import spans
from . import watchdog
from .spans import (Span, SpanContext, span, record_span, current_span,
                    current_context)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "DEFAULT_BUCKETS", "OVERFLOW_LABEL",
    "counter", "gauge", "histogram", "export_text", "reset",
    "new_request_id", "current_request_id", "set_current_request_id",
    "request_scope", "REQUEST_ID_HEADER",
    "start_periodic_flush", "stop_periodic_flush", "flush_to_file",
    "devstats", "faultlab", "flightrec", "history", "numwatch",
    "profstats", "slo", "spans", "watchdog",
    "Span", "SpanContext", "span", "record_span", "current_span",
    "current_context",
]

_flush_lock = threading.Lock()
_flush_stop = None        # threading.Event of the running flusher, or None
_flush_thread = None


def flush_to_file(path=None):
    """Write the full exposition atomically (tmp + rename) so a concurrent
    reader (textfile collector, tail) never sees a torn file. The tmp name
    carries pid AND thread id: the periodic flusher and a one-shot
    flush_to_file() call in the same process must never interleave writes
    into one tmp file."""
    from .. import config
    if path is None:
        path = config.get_env("MXTPU_TELEMETRY_FILE")
    tmp = "%s.%d.%d.tmp" % (path, os.getpid(), threading.get_ident())
    with open(tmp, "w") as f:
        f.write(export_text())
    os.replace(tmp, path)
    return path


def start_periodic_flush(path=None, interval_s=None):
    """Flush the registry to ``path`` every ``interval_s`` seconds from a
    daemon thread (defaults: MXTPU_TELEMETRY_FILE / MXTPU_TELEMETRY_FLUSH_S).
    Idempotent: a second call restarts with the new settings. Returns the
    resolved path."""
    from .. import config
    global _flush_stop, _flush_thread
    if interval_s is None:
        interval_s = config.get_env("MXTPU_TELEMETRY_FLUSH_S")
    interval_s = max(0.05, float(interval_s))
    if path is None:
        path = config.get_env("MXTPU_TELEMETRY_FILE")

    def run(stop):
        while not stop.wait(interval_s):
            try:
                flush_to_file(path)
            except Exception:
                # a full disk / unwritable path must not kill the job the
                # telemetry exists to observe — but the skip must not be
                # silent either (R005): debug-log it so a flusher that
                # never lands a file is diagnosable
                logging.getLogger(__name__).debug(
                    "telemetry flush to %r failed", path, exc_info=True)
        try:                      # final flush so short jobs leave a file
            flush_to_file(path)
        except Exception:
            logging.getLogger(__name__).debug(
                "final telemetry flush to %r failed", path, exc_info=True)

    # stop-old + register-new is ONE critical section: concurrent starts
    # must never orphan a running flusher (its Event would be lost and the
    # thread unstoppable for process lifetime)
    with _flush_lock:
        _stop_locked()
        stop = threading.Event()
        t = threading.Thread(target=run, args=(stop,), daemon=True,
                             name="mxtpu-telemetry")
        _flush_stop, _flush_thread = stop, t
        t.start()
    return path


def _stop_locked():
    """Signal + join the current flusher; caller holds _flush_lock (the
    flusher thread itself never takes the lock, so joining under it is
    deadlock-free)."""
    global _flush_stop, _flush_thread
    stop, t = _flush_stop, _flush_thread
    _flush_stop = _flush_thread = None
    if stop is not None:
        stop.set()
        if t is not None:
            t.join(timeout=5.0)


def stop_periodic_flush():
    """Stop the flusher; the thread writes one final snapshot on exit so
    short jobs always leave a file behind."""
    with _flush_lock:
        _stop_locked()


def _maybe_autostart():
    """Package-import hook: MXTPU_TELEMETRY_FLUSH_S > 0 starts the flusher
    (headless training jobs get metrics with zero code changes), the
    flight recorder chains its crash-dump excepthooks (gated per-crash by
    MXTPU_FLIGHTREC_DUMP_ON_CRASH), and MXTPU_WATCHDOG=1 starts the stall
    watchdog monitor."""
    from .. import config
    try:
        if config.get_env("MXTPU_TELEMETRY_FLUSH_S") > 0:
            start_periodic_flush()
    except Exception:
        pass
    try:
        flightrec.install_crash_dump()
    except Exception:
        pass
    try:
        if config.get_env("MXTPU_WATCHDOG"):
            watchdog.start()
    except Exception:
        pass
    try:
        if config.get_env("MXTPU_DEVSTATS"):
            devstats.start()
    except Exception:
        pass
    try:
        if config.get_env("MXTPU_PROFSTATS"):
            profstats.start()
    except Exception:
        pass
    try:
        if config.get_env("MXTPU_HISTORY"):
            history.start()
    except Exception:
        pass
