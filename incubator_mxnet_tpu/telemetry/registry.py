"""Process-wide metrics registry with Prometheus text exposition.

One registry serves every subsystem (serving, training, kvstore, data IO):
named counters, gauges, and fixed-bucket histograms, each with optional
labels, all behind one lock-per-metric design cheap enough to stay on for
every request and every training step. ``export_text()`` renders the
Prometheus text format (version 0.0.4) without any external dependency —
the serving front-end serves it at ``GET /metrics`` and headless jobs
flush it to a file (telemetry.start_periodic_flush).

Design points:

- *Get-or-create*: ``counter(name, ...)`` returns the existing metric on
  repeat calls so every module can declare its metrics at import time
  without coordinating ownership; a re-declaration with a different type
  or label set raises loudly (silent divergence would corrupt exposition).
- *Bounded label cardinality*: a metric accepts at most
  ``MXTPU_TELEMETRY_MAX_SERIES`` distinct label combinations; past the
  bound new combinations are clamped onto the ``"_other_"`` series with a
  one-time RuntimeWarning — an unbounded label (request IDs, user IDs)
  must never OOM the process or melt the scrape.
- *Closed-right histogram buckets*: an observation lands in every bucket
  whose upper bound ``le`` is >= the value (Prometheus ``le`` is an
  INCLUSIVE upper bound); exposition is cumulative with a ``+Inf``
  terminal bucket, ``_sum`` and ``_count``.
"""
from __future__ import annotations

import bisect
import math
import re
import threading
import warnings

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "export_text", "reset",
           "DEFAULT_BUCKETS", "OVERFLOW_LABEL"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Series a metric folds overflow label combinations onto once the
#: cardinality bound is hit (every label value becomes this sentinel).
OVERFLOW_LABEL = "_other_"

#: Default histogram buckets (seconds-flavored; pass explicit buckets for
#: anything that is not a small latency).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0)


def _max_series():
    # read lazily so MXTPU_TELEMETRY_MAX_SERIES set before first overflow
    # takes effect without an import-order dance
    from .. import config
    return max(1, config.get_env("MXTPU_TELEMETRY_MAX_SERIES"))


def _escape_label_value(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(h):
    return str(h).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v):
    """Prometheus sample value: integers render without a trailing .0."""
    if isinstance(v, float):
        if v == math.inf:
            return "+Inf"
        if v == -math.inf:
            return "-Inf"
        if v != v:  # NaN
            return "NaN"
        if v.is_integer() and abs(v) < 1e15:
            return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class _Metric:
    """Shared label handling: series keyed by the label-value tuple."""

    type_name = "untyped"

    def __init__(self, name, help, labelnames=()):
        if not _NAME_RE.match(name):
            raise ValueError("invalid metric name %r" % name)
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError("invalid label name %r (metric %r)"
                                 % (ln, name))
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series = {}            # label-value tuple -> series state
        self._overflowed = False

    def _key(self, labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                "metric %r takes labels %r, got %r"
                % (self.name, self.labelnames, tuple(sorted(labels))))
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _series_for(self, labels, factory):
        """Resolve (creating if needed) the series for a label set, with
        the cardinality clamp. Caller holds no lock; this takes it."""
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= _max_series() and self.labelnames:
                    if not self._overflowed:
                        self._overflowed = True
                        warnings.warn(
                            "metric %r exceeded MXTPU_TELEMETRY_MAX_SERIES "
                            "(%d) distinct label sets — new label values are "
                            "clamped onto %r. An unbounded label (request "
                            "id, user id, raw path) does not belong on a "
                            "metric." % (self.name, _max_series(),
                                         OVERFLOW_LABEL),
                            RuntimeWarning, stacklevel=4)
                    key = tuple(OVERFLOW_LABEL for _ in self.labelnames)
                    s = self._series.get(key)
                if s is None:
                    s = factory()
                    self._series[key] = s
            return s

    def remove(self, **labels):
        """Drop one series (e.g. a gauge callback whose owner is being
        unloaded — a dead model must not export stale depth forever nor
        pin its queue in memory). No-op if the series never existed."""
        key = self._key(labels)
        with self._lock:
            self._series.pop(key, None)

    def _label_str(self, key, extra=""):
        parts = ['%s="%s"' % (ln, _escape_label_value(v))
                 for ln, v in zip(self.labelnames, key)]
        if extra:
            parts.append(extra)
        return "{%s}" % ",".join(parts) if parts else ""

    def _header_lines(self):
        return ["# HELP %s %s" % (self.name, _escape_help(self.help)),
                "# TYPE %s %s" % (self.name, self.type_name)]


class Counter(_Metric):
    """Monotonically increasing value (use a Gauge for anything that can
    fall). ``inc(n, **labels)``; negative increments raise."""

    type_name = "counter"

    def inc(self, n=1, **labels):
        if n < 0:
            raise ValueError("counter %r cannot decrease (inc %r)"
                             % (self.name, n))
        s = self._series_for(labels, lambda: [0])
        with self._lock:
            s[0] += n

    def value(self, **labels):
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            return s[0] if s is not None else 0

    def collect(self):
        lines = self._header_lines()
        with self._lock:
            for key in sorted(self._series):
                lines.append("%s%s %s" % (self.name, self._label_str(key),
                                          _fmt(self._series[key][0])))
        return lines

    def series(self):
        """[(labels_dict, value)] snapshot for programmatic consumers
        (devstats.dispatch_totals sums windows over every label set —
        exposition-text parsing is for scrapers, not in-process code)."""
        with self._lock:
            items = [(key, s[0]) for key, s in sorted(self._series.items())]
        return [(dict(zip(self.labelnames, key)), v) for key, v in items]


class Gauge(_Metric):
    """Point-in-time value: ``set``/``inc``/``dec``, or ``set_function`` to
    sample a callable at exposition time (queue depths, cache sizes)."""

    type_name = "gauge"

    def set(self, v, **labels):
        s = self._series_for(labels, lambda: [0.0])
        with self._lock:
            # same guard as inc/dec: a series bound to a live sampler via
            # set_function() must not be silently frozen to a constant
            if callable(s[0]):
                raise ValueError(
                    "gauge %r series is bound to a callback via "
                    "set_function(); set() would silently detach the "
                    "live sampler (use set_function again, or "
                    "remove_function first)" % self.name)
            s[0] = v

    def inc(self, n=1, **labels):
        s = self._series_for(labels, lambda: [0.0])
        with self._lock:
            if callable(s[0]):
                raise ValueError(
                    "gauge %r series is bound to a callback via "
                    "set_function(); inc/dec would silently detach the "
                    "live sampler" % self.name)
            s[0] += n

    def dec(self, n=1, **labels):
        self.inc(-n, **labels)

    def set_function(self, fn, **labels):
        """Bind the series to ``fn() -> number``, evaluated per export."""
        s = self._series_for(labels, lambda: [0.0])
        with self._lock:
            s[0] = fn

    def remove_function(self, fn):
        """Drop every series bound to exactly ``fn`` (identity compare).
        The safe unbind for an owner being torn down: a label-keyed
        remove() could delete a NEWER owner's series after a reload race,
        or miss a series the cardinality clamp re-keyed onto the overflow
        label — identity can do neither. No-op if fn is not bound."""
        if fn is None:
            return
        with self._lock:
            for k in [k for k, s in self._series.items() if s[0] is fn]:
                self._series.pop(k)

    def value(self, **labels):
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            raw = s[0] if s is not None else 0.0
        if callable(raw):
            try:
                return raw()
            except Exception:
                return 0.0
        return raw

    def collect(self):
        lines = self._header_lines()
        for key, val in self._evaluated():
            lines.append("%s%s %s" % (self.name, self._label_str(key),
                                      _fmt(val)))
        return lines

    def _evaluated(self):
        """[(key_tuple, float)] with set_function callbacks sampled NOW —
        the one place gauge callbacks are evaluated, shared by text
        exposition and the programmatic series() walk (evaluating an SLO
        gauge advances its alert state machine; both consumers must drive
        it identically)."""
        with self._lock:
            items = [(key, s[0]) for key, s in sorted(self._series.items())]
        out = []
        for key, raw in items:
            try:
                if callable(raw):
                    raw = raw()
                val = float(raw)
            except Exception:  # a dead/None-returning callback must not
                val = 0.0      # kill the scrape
            out.append((key, val))
        return out

    def series(self):
        """[(labels_dict, value)] snapshot with callbacks evaluated — the
        programmatic mirror of Counter.series() for in-process consumers
        (the history self-scrape reads depth/burn gauges through this
        instead of re-parsing its own process's exposition text)."""
        return [(dict(zip(self.labelnames, key)), v)
                for key, v in self._evaluated()]


class Histogram(_Metric):
    """Fixed-bucket histogram. Buckets are CLOSED-RIGHT: an observation
    equal to a boundary counts in that boundary's bucket (Prometheus
    ``le`` semantics); exposition is cumulative with ``+Inf``/_sum/_count."""

    type_name = "histogram"

    def __init__(self, name, help, buckets=DEFAULT_BUCKETS, labelnames=()):
        super().__init__(name, help, labelnames)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram %r needs at least one bucket" % name)
        if bounds[-1] == math.inf:
            bounds = bounds[:-1]
        self.buckets = tuple(bounds)

    def _new_series(self):
        # per-bucket NON-cumulative counts + [sum, count]; cumulated at
        # exposition so observe() touches exactly one bucket slot
        return {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0,
                "count": 0}

    def observe(self, v, **labels):
        v = float(v)
        s = self._series_for(labels, self._new_series)
        # closed-right: first bucket with bound >= v; bisect_left returns
        # exactly that index (the +Inf overflow slot is the final index)
        lo = bisect.bisect_left(self.buckets, v)
        with self._lock:
            s["counts"][lo] += 1
            s["sum"] += v
            s["count"] += 1

    def value(self, **labels):
        """(sum, count) for one series — the cheap programmatic read."""
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            return (s["sum"], s["count"]) if s is not None else (0.0, 0)

    def series(self):
        """[(labels_dict, (sum, count))] snapshot — the programmatic
        mirror of Counter.series(); the history self-scrape derives
        per-tick mean latency from the sum/count deltas."""
        with self._lock:
            items = [(key, (s["sum"], s["count"]))
                     for key, s in sorted(self._series.items())]
        return [(dict(zip(self.labelnames, key)), v) for key, v in items]

    def bucket_counts(self, **labels):
        """CUMULATIVE counts per bucket bound (+Inf last) — test hook."""
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            raw = list(s["counts"]) if s is not None \
                else [0] * (len(self.buckets) + 1)
        out, acc = [], 0
        for c in raw:
            acc += c
            out.append(acc)
        return out

    def collect(self):
        lines = self._header_lines()
        with self._lock:
            items = [(key, [list(s["counts"]), s["sum"], s["count"]])
                     for key, s in sorted(self._series.items())]
        for key, (counts, total, count) in items:
            acc = 0
            for bound, c in zip(self.buckets, counts):
                acc += c
                lines.append("%s_bucket%s %d" % (
                    self.name,
                    self._label_str(key, 'le="%s"' % _fmt(float(bound))),
                    acc))
            acc += counts[-1]
            lines.append("%s_bucket%s %d" % (
                self.name, self._label_str(key, 'le="+Inf"'), acc))
            lines.append("%s_sum%s %s" % (self.name, self._label_str(key),
                                          _fmt(float(total))))
            lines.append("%s_count%s %d" % (self.name, self._label_str(key),
                                            count))
        return lines


class MetricsRegistry:
    """Thread-safe name -> metric map with get-or-create declaration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _declare(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        "metric %r already registered as %s, not %s"
                        % (name, type(m).type_name, cls.type_name))
                if m.labelnames != tuple(labelnames):
                    raise ValueError(
                        "metric %r already registered with labels %r, not %r"
                        % (name, m.labelnames, tuple(labelnames)))
                if "buckets" in kw:
                    bounds = sorted(float(b) for b in kw["buckets"]
                                    if float(b) != math.inf)
                    if tuple(bounds) != m.buckets:
                        raise ValueError(
                            "histogram %r already registered with buckets "
                            "%r, not %r — observations would silently land "
                            "in the wrong bounds"
                            % (name, m.buckets, tuple(bounds)))
                return m
            m = cls(name, help, labelnames=labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()):
        return self._declare(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._declare(Gauge, name, help, labelnames)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS,
                  labelnames=()):
        m = self._declare(Histogram, name, help, labelnames, buckets=buckets)
        return m

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def samples(self):
        """Every numeric sample in the registry as ``(name, kind,
        labels_dict, value)`` tuples, sorted by metric name — the
        registry-iteration API the metric-history self-scrape
        (telemetry/history.py) walks each tick. Counters and gauges
        yield one sample per label set (gauge callbacks evaluated NOW,
        exactly like text exposition); histograms yield ``<name>_sum``
        and ``<name>_count`` samples so rate rules can derive per-tick
        means without parsing exposition text."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        out = []
        for m in metrics:
            kind = m.type_name
            if isinstance(m, Histogram):
                for labels, (total, count) in m.series():
                    out.append((m.name + "_sum", kind, labels,
                                float(total)))
                    out.append((m.name + "_count", kind, labels,
                                float(count)))
            else:
                for labels, v in m.series():
                    out.append((m.name, kind, labels, float(v)))
        return out

    def export_text(self):
        """The full Prometheus text exposition (format version 0.0.4)."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines = []
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        """Zero every metric's series IN PLACE — test isolation only;
        production metrics are process-lifetime cumulative. The metric
        objects themselves stay registered: modules cache them at import
        time, and dropping the name->metric map would orphan those caches
        (updates still applied, but invisible to every future export)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                m._series.clear()
                m._overflowed = False


#: The process-wide default registry every subsystem instruments against.
REGISTRY = MetricsRegistry()


def counter(name, help="", labelnames=()):
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", buckets=DEFAULT_BUCKETS, labelnames=()):
    return REGISTRY.histogram(name, help, buckets=buckets,
                              labelnames=labelnames)


def export_text():
    return REGISTRY.export_text()


def reset():
    REGISTRY.reset()
