"""Numerical-health observability (the numerics sentinel): value-level
taps + shadow-sampled divergence tracking.

Every other telemetry layer here measures *time and structure* (spans,
devstats MFU, profstats hotspots, SLO burn rates); this one observes the
*values* flowing through the system — the NaN storm in training, the
drifting int8 logits in serving, the non-finite decode logits a sampler
would silently turn into garbage tokens.

Two halves:

**On-device stats taps** — ``tap(model, site, leaves)`` runs a tiny
reducer program over a tensor tree ON DEVICE and brings back one packed
scalar bundle ``[finite_fraction, abs_max, rms]`` in a single
device->host transfer. Reducers are AOT-compiled once per shape/dtype
signature through ``aot.compile_cached`` (kind ``"numwatch"`` — the aot
hit/miss counters attribute them, and steady state never recompiles).
Tap sites are stride-sampled by ``MXTPU_NUMWATCH_SAMPLE`` (0 disables —
the default; 1.0 taps every dispatch; 0.25 every 4th — deterministic
stride, not random, so two identical runs tap identical dispatches).
Call sites today: TrainStep loss/updated params (jit.py), serving
dispatch outputs (serving/batcher.py), and the decode loop's logits
(serving/generate.py, via the fused per-row finiteness output).

Non-finite detections increment
``mxtpu_numwatch_nonfinite_total{model,site}`` and fire a once-per-
episode ``nan_storm`` flight-recorder event with hysteresis (the
devstats hbm_pressure / watchdog precedent): the first non-finite tap at
a site opens an episode and records the event; further non-finite taps
in the same episode are counted but not re-recorded; a fully-finite tap
closes the episode and re-arms it. Rolling abs-max / rms land in
``mxtpu_numwatch_absmax{model,site}`` / ``mxtpu_numwatch_rms{...}``.

**Shadow execution sampling** — ``register_shadow(model, reference)``
attaches a reference servable (e.g. the bf16 original of an
int8-quantized deployment) to a served model. A deterministic stride
(``MXTPU_SHADOW_SAMPLE``) of dispatched batches is re-executed through
the reference OFF the hot path (a single daemon worker thread with a
bounded queue — overload drops samples, never delays serving) and the
primary/reference outputs are compared: max-abs-diff, top-1 agreement
and mean logit KL land in ``mxtpu_shadow_divergence{model,metric}``.
A max-abs-diff above ``MXTPU_SHADOW_THRESHOLD`` is a BREACH: the
``on_breach`` callback (the serving registry wires it to the model
entry's degraded flag — the hlolint refusal shape) fires once per
breach episode together with a ``shadow_breach`` flightrec event.

Everything in this module follows the R005 discipline: a telemetry
failure must never fail the traffic it observes — every public entry
point swallows exceptions into a debug log.

Surfaces: ``describe()`` backs ``GET /debug/numerics`` (serving/server)
and loadgen's between-stage scrape; ``detach_model()`` is called from
the batcher/generator close paths so an unloaded model exports no
frozen series (the detach-on-close contract).
"""
from __future__ import annotations

import logging
import math
import queue as _queue
import threading

import numpy as onp

from .registry import counter, gauge
from . import faultlab
from . import flightrec

__all__ = ["tap", "note", "shadow_offer", "register_shadow",
           "unregister_shadow", "shadow_drain", "describe", "detach_model",
           "reset", "sample_stride", "shadow_stride"]

_LOG = logging.getLogger(__name__)

_NONFINITE = counter(
    "mxtpu_numwatch_nonfinite_total",
    "Sampled taps that observed at least one non-finite element",
    ("model", "site"))
_TAPS = counter(
    "mxtpu_numwatch_taps_total",
    "Sampled numerics taps executed (per model and tap site)",
    ("model", "site"))
_ABSMAX = gauge(
    "mxtpu_numwatch_absmax",
    "Rolling abs-max over the last sampled tap (non-finite masked out)",
    ("model", "site"))
_RMS = gauge(
    "mxtpu_numwatch_rms",
    "Rolling rms over the last sampled tap (non-finite masked out)",
    ("model", "site"))
_SHADOW_DIV = gauge(
    "mxtpu_shadow_divergence",
    "Primary-vs-reference divergence of the last shadow sample "
    "(metric: max_abs_diff | top1_agreement | logit_kl)",
    ("model", "metric"))
_SHADOW_SAMPLES = counter(
    "mxtpu_shadow_samples_total",
    "Batches re-executed through the registered reference servable",
    ("model",))
_SHADOW_BREACHES = counter(
    "mxtpu_shadow_breaches_total",
    "Shadow samples whose max-abs-diff exceeded MXTPU_SHADOW_THRESHOLD",
    ("model",))
_SHADOW_DROPS = counter(
    "mxtpu_shadow_drops_total",
    "Shadow samples dropped because the worker queue was full",
    ("model",))

_lock = threading.Lock()
_tap_counts = {}        # (model, site) -> dispatches seen (stride clock)
_storms = set()         # (model, site) keys inside a nan_storm episode
_storm_counts = {}      # (model, site) -> episodes fired (describe)
_last_stats = {}        # (model, site) -> (finite_frac, absmax, rms)


def sample_stride():
    """Tap stride from MXTPU_NUMWATCH_SAMPLE: 0 -> disabled (stride 0),
    rate r in (0, 1] -> every round(1/r)-th dispatch."""
    from .. import config
    try:
        rate = float(config.get_env("MXTPU_NUMWATCH_SAMPLE") or 0.0)
    except Exception:
        return 0
    if rate <= 0.0:
        return 0
    return max(1, int(round(1.0 / min(1.0, rate))))


def shadow_stride():
    """Shadow stride from MXTPU_SHADOW_SAMPLE (same 0-disables contract)."""
    from .. import config
    try:
        rate = float(config.get_env("MXTPU_SHADOW_SAMPLE") or 0.0)
    except Exception:
        return 0
    if rate <= 0.0:
        return 0
    return max(1, int(round(1.0 / min(1.0, rate))))


# --------------------------------------------------------------- reducers
def _leaf_data(a):
    """Unwrap NDArray (_data) and leave jax/numpy arrays alone."""
    return getattr(a, "_data", a)


def _reducer_entry(sig):
    """AOT-cached packed reducer for one input signature: returns the
    cache entry whose .fn maps the leaves to a float32[3] bundle
    [finite_fraction, abs_max, rms] — ONE device->host transfer for the
    whole tree, compiled once per signature (aot kind='numwatch')."""
    from .. import aot

    key = aot.cache_key("numwatch", sig, kind="numwatch",
                        extra=(len(sig),))

    def build():
        import jax
        import jax.numpy as jnp
        specs = [jax.ShapeDtypeStruct(shape, jnp.dtype(dt))
                 for shape, dt in sig]
        total = max(1, sum(int(onp.prod(s or (1,))) for s, _ in sig))

        def reduce_stats(*leaves):
            finite = jnp.asarray(0.0, jnp.float32)
            absmax = jnp.asarray(0.0, jnp.float32)
            sumsq = jnp.asarray(0.0, jnp.float32)
            for leaf in leaves:
                x = leaf.astype(jnp.float32)
                ok = jnp.isfinite(x)
                finite = finite + jnp.sum(ok).astype(jnp.float32)
                masked = jnp.where(ok, x, 0.0)
                absmax = jnp.maximum(absmax, jnp.max(jnp.abs(masked)))
                sumsq = sumsq + jnp.sum(masked * masked)
            return jnp.stack([finite / total, absmax,
                              jnp.sqrt(sumsq / total)])

        fn = jax.jit(reduce_stats).lower(*specs).compile()
        return fn, None, None

    return aot.compile_cached(key, build)


def tap(model, site, leaves):
    """Stride-sampled on-device stats tap over ``leaves`` (a flat list of
    NDArray / jax / numpy arrays). Never raises; never blocks beyond the
    one scalar-bundle transfer. Call from hot paths freely — an unsampled
    call is a dict increment under a lock."""
    try:
        stride = sample_stride()
        if stride <= 0:
            return None
        k = (str(model), str(site))
        with _lock:
            n = _tap_counts.get(k, 0)
            _tap_counts[k] = n + 1
        if n % stride != 0:
            return None
        leaves = [_leaf_data(a) for a in leaves
                  if hasattr(a, "shape") and hasattr(a, "dtype")]
        if not leaves:
            return None
        sig = tuple((tuple(int(d) for d in a.shape), str(a.dtype))
                    for a in leaves)
        entry = _reducer_entry(sig)
        # reviewed sync point: the packed [finite_frac, absmax, rms]
        # bundle is the tap's entire host traffic
        bundle = onp.asarray(entry.fn(*leaves))  # mxtpulint: disable=R001
        return note(model, site,
                    float(bundle[0]), float(bundle[1]), float(bundle[2]))
    except Exception:
        _LOG.debug("numwatch tap at %s/%s dropped", model, site,
                   exc_info=True)
        return None


def note(model, site, finite_frac, absmax=None, rms=None):
    """Record one observation's health facts (the tap's back half, also
    called directly by sites that compute finiteness inside their own
    compiled program — the decode loop's fused per-row check). Applies
    the counter/gauge updates and the nan_storm hysteresis: an episode
    OPENS (event fires once) when finite_frac drops below 1.0 and CLOSES
    (re-arms) on the next fully-finite observation."""
    try:
        model, site = str(model), str(site)
        _TAPS.inc(model=model, site=site)
        if absmax is not None:
            _ABSMAX.set(float(absmax), model=model, site=site)
        if rms is not None:
            _RMS.set(float(rms), model=model, site=site)
        k = (model, site)
        fire = False
        with _lock:
            _last_stats[k] = (float(finite_frac), absmax, rms)
            in_episode = k in _storms
            if finite_frac < 1.0 and not in_episode:
                _storms.add(k)
                _storm_counts[k] = _storm_counts.get(k, 0) + 1
                fire = True
            elif finite_frac >= 1.0 and in_episode:
                _storms.discard(k)
        if finite_frac < 1.0:
            _NONFINITE.inc(model=model, site=site)
        if fire:
            # outside the lock (devstats precedent): flightrec never
            # raises, but it must not serialize the tap path either
            flightrec.record("nan_storm", model=model, site=site,
                            finite_frac=round(float(finite_frac), 6))
        return bool(finite_frac >= 1.0)
    except Exception:
        _LOG.debug("numwatch note at %s/%s dropped", model, site,
                   exc_info=True)
        return None


# --------------------------------------------------------- shadow sampling
class _Shadow:
    """One model's registered reference + its stride clock and episode."""

    __slots__ = ("reference", "stride", "threshold", "on_breach",
                 "count", "breached", "last")

    def __init__(self, reference, stride, threshold, on_breach):
        self.reference = reference
        self.stride = stride
        self.threshold = threshold
        self.on_breach = on_breach
        self.count = 0          # dispatches seen (stride clock)
        self.breached = False   # inside a breach episode
        self.last = None        # last comparison dict (describe)


_shadows = {}                   # model -> _Shadow
_shadow_q = None                # _queue.Queue of (model, stacked, primary)
_shadow_thread = None
_SHADOW_QUEUE_SIZE = 64


def register_shadow(model, reference, stride=None, threshold=None,
                    on_breach=None):
    """Attach ``reference`` (a servable with predict_batch, or a bare
    callable) as ``model``'s shadow. ``stride`` defaults to the
    MXTPU_SHADOW_SAMPLE-derived stride resolved at offer time; ``threshold``
    to MXTPU_SHADOW_THRESHOLD. ``on_breach(reason)`` fires once per breach
    episode (the registry wires the degraded-health flip here)."""
    with _lock:
        _shadows[str(model)] = _Shadow(reference, stride, threshold,
                                       on_breach)
    _ensure_worker()


def unregister_shadow(model):
    with _lock:
        return _shadows.pop(str(model), None) is not None


def _ensure_worker():
    global _shadow_q, _shadow_thread
    with _lock:
        if _shadow_thread is not None and _shadow_thread.is_alive():
            return
        _shadow_q = _queue.Queue(maxsize=_SHADOW_QUEUE_SIZE)
        _shadow_thread = threading.Thread(
            target=_shadow_loop, args=(_shadow_q,), daemon=True,
            name="mxtpu-numwatch-shadow")
        _shadow_thread.start()


def _shadow_loop(q):
    while True:
        model, stacked, primary = q.get()
        try:
            # faultlab site "numwatch.shadow": an injected exception here
            # becomes a DROPPED sample (debug-logged below) — proof that
            # telemetry failure never fails traffic (R005 discipline)
            if faultlab.armed:
                faultlab.fire("numwatch.shadow", model=model)
            _shadow_compare(model, stacked, primary)
        except Exception:
            _LOG.debug("shadow comparison for model %r dropped", model,
                       exc_info=True)
        finally:
            q.task_done()


def shadow_offer(model, stacked, primary_outs):
    """Hot-path hook (serving/batcher, AFTER results landed on host):
    stride-sample this dispatch into the shadow worker's bounded queue.
    Full queue -> sample dropped and counted, never blocks serving."""
    try:
        model = str(model)
        with _lock:
            sh = _shadows.get(model)
            if sh is None:
                return
            n = sh.count
            sh.count = n + 1
            stride = sh.stride
        if stride is None:
            stride = shadow_stride()
        if stride <= 0 or n % stride != 0:
            return
        q = _shadow_q
        if q is None:
            return
        try:
            # not a device sync: the batcher hands over outputs it ALREADY
            # materialized on host for slicing — asarray is a no-op wrap
            q.put_nowait((model, tuple(stacked),
                          tuple(onp.asarray(o)  # mxtpulint: disable=R001
                                for o in primary_outs)))
        except _queue.Full:
            _SHADOW_DROPS.inc(model=model)
    except Exception:
        _LOG.debug("shadow offer for model %r dropped", model,
                   exc_info=True)


def shadow_drain(timeout=10.0):
    """Block until every queued shadow sample has been compared (tests /
    CI determinism; the serving path never calls this)."""
    q = _shadow_q
    if q is None:
        return True
    deadline = threading.Event()
    t = threading.Thread(target=lambda: (q.join(), deadline.set()),
                         daemon=True)
    t.start()
    return deadline.wait(timeout)


def _softmax(x):
    x = x.astype(onp.float64)
    x = x - x.max(axis=-1, keepdims=True)
    e = onp.exp(x)
    return e / e.sum(axis=-1, keepdims=True)


def _shadow_compare(model, stacked, primary):
    sh = _shadows.get(model)
    if sh is None:
        return
    ref_outs = sh.reference.predict_batch(*stacked) \
        if hasattr(sh.reference, "predict_batch") else sh.reference(*stacked)
    if not isinstance(ref_outs, (list, tuple)):
        ref_outs = (ref_outs,)
    # reviewed sync point: the shadow worker thread owns this transfer —
    # it is off the serving hot path by construction
    p = onp.asarray(primary[0], dtype=onp.float64)
    r = onp.asarray(
        _leaf_data(ref_outs[0]), dtype=onp.float64)
    if p.shape != r.shape:
        raise ValueError("shadow output shape %s != primary %s"
                         % (r.shape, p.shape))
    max_abs = float(onp.max(onp.abs(p - r))) if p.size else 0.0
    comparison = {"max_abs_diff": max_abs}
    if p.ndim >= 2 and p.shape[-1] > 1:
        comparison["top1_agreement"] = float(
            onp.mean(p.argmax(axis=-1) == r.argmax(axis=-1)))
        sp, sr = _softmax(p), _softmax(r)
        comparison["logit_kl"] = float(onp.mean(onp.sum(
            sr * (onp.log(sr + 1e-12) - onp.log(sp + 1e-12)), axis=-1)))
    for metric, value in comparison.items():
        _SHADOW_DIV.set(value, model=model, metric=metric)
    _SHADOW_SAMPLES.inc(model=model)

    from .. import config
    threshold = sh.threshold
    if threshold is None:
        threshold = float(config.get_env("MXTPU_SHADOW_THRESHOLD"))
    breach = max_abs > threshold
    fire = False
    with _lock:
        sh.last = dict(comparison, breach=breach, threshold=threshold)
        if breach and not sh.breached:
            sh.breached = True
            fire = True
        elif not breach and sh.breached:
            # recovery re-arms the episode; the degraded flag the
            # registry set stays sticky until the next load (the
            # hlolint-refusal shape: an operator decision, not a flap)
            sh.breached = False
    if breach:
        _SHADOW_BREACHES.inc(model=model)
    if fire:
        reason = ("shadow divergence breach: max_abs_diff=%.4g > "
                  "threshold=%.4g" % (max_abs, threshold))
        flightrec.record("shadow_breach", model=model,
                         max_abs_diff=round(max_abs, 6),
                         threshold=threshold)
        cb = sh.on_breach
        if cb is not None:
            try:
                cb(reason)
            except Exception:
                _LOG.debug("shadow on_breach callback for %r failed",
                           model, exc_info=True)


# ------------------------------------------------------------- inspection
def describe():
    """JSON-able snapshot (GET /debug/numerics, loadgen scrape)."""
    with _lock:
        taps = {"%s/%s" % k: {"sampled": _TAPS.value(model=k[0], site=k[1]),
                              "nonfinite": _NONFINITE.value(model=k[0],
                                                            site=k[1]),
                              "storms": _storm_counts.get(k, 0),
                              "in_storm": k in _storms,
                              "last": list(_last_stats.get(k) or ())}
                for k in sorted(_last_stats)}
        shadows = {m: {"stride": sh.stride, "threshold": sh.threshold,
                       "offered": sh.count,
                       "samples": _SHADOW_SAMPLES.value(model=m),
                       "breaches": _SHADOW_BREACHES.value(model=m),
                       "drops": _SHADOW_DROPS.value(model=m),
                       "breached": sh.breached,
                       "last": dict(sh.last) if sh.last else None}
                   for m, sh in sorted(_shadows.items())}
    return {"sample_stride": sample_stride(),
            "shadow_stride": shadow_stride(),
            "taps": taps, "shadows": shadows}


def detach_model(model):
    """Drop every series and episode this model drove (the detach-on-close
    contract: an unloaded model must not export frozen health). Called
    from the batcher/generator close paths; never raises."""
    model = str(model)
    try:
        with _lock:
            keys = [k for k in _last_stats if k[0] == model]
            for k in keys:
                _last_stats.pop(k, None)
                _tap_counts.pop(k, None)
                _storm_counts.pop(k, None)
                _storms.discard(k)
            _shadows.pop(model, None)
        for _, site in keys:
            for g in (_ABSMAX, _RMS):
                try:
                    g.remove(model=model, site=site)
                except Exception:
                    pass
        for metric in ("max_abs_diff", "top1_agreement", "logit_kl"):
            try:
                _SHADOW_DIV.remove(model=model, metric=metric)
            except Exception:
                pass
    except Exception:
        _LOG.debug("numwatch detach for model %r dropped", model,
                   exc_info=True)


def reset():
    """Test hook: forget every episode, stride clock and shadow."""
    with _lock:
        _tap_counts.clear()
        _storms.clear()
        _storm_counts.clear()
        _last_stats.clear()
        _shadows.clear()
