"""Device-truth observability: XLA cost/memory analysis, live HBM gauges,
and per-dispatch MFU attribution.

Every signal the stack exported before this module was host wall-clock —
good enough to say a step got slower, useless to say WHY. Production
frameworks judge runs by achieved utilization against hardware peaks
(TensorFlow, arXiv 1605.08695; MLPerf TPU-pod scaling, arXiv 1909.09756),
and the attribution chain needs device facts at three timescales:

- **Per program** (``program_stats``): at AOT build/load time the compiled
  executable's ``cost_analysis()`` + ``memory_analysis()`` are harvested
  ONCE into ``{flops, bytes_accessed, peak_bytes, output_bytes}`` and
  stored on the aot.CACHE entry (and in the persisted artifact header, so
  a zero-compile artifact load in a fresh process still has them —
  docs/AOT.md). Exposed as ``mxtpu_aot_program_flops`` /
  ``mxtpu_aot_program_peak_bytes{model,kind,bucket}`` and on
  ``GET /debug/aot``. Harvesting per DISPATCH instead would put an XLA
  analysis walk into the hot path — mxtpulint R001 models exactly that
  defect.
- **Per dispatch** (``observe_dispatch``): the hot paths (TrainStep,
  EvalStep, ServedModel / MeshServable under the batcher) divide the
  entry's FLOPs by the measured block-until-ready dispatch span, driving
  rolling ``mxtpu_device_mfu{model,kind,replica}`` and
  ``mxtpu_device_hbm_bw_util{model,kind,replica}`` gauges against the
  per-backend peak table, plus ``mxtpu_device_flops_total`` /
  ``mxtpu_device_bytes_accessed_total`` /
  ``mxtpu_device_dispatch_seconds_total`` counters so a scrape WINDOW
  (a loadgen stage, a CI soak) can compute its own achieved utilization
  from deltas. Whether a step is compute-bound (MFU high), HBM-bound
  (bw_util high, MFU low) or host-overhead-bound (both low while
  wall-clock is busy) is now a scrape, not a guess.
- **Continuous** (the HBM sampler): a watchdog-style daemon polls
  ``device.memory_stats()`` into ``mxtpu_device_memory_bytes{device,stat}``
  and files a flight-recorder ``hbm_pressure`` event once per episode
  when a device crosses 90% of its memory limit. Backends whose PJRT
  client reports no memory stats (CPU) degrade to host-RSS report-only
  samples under ``device="host"`` so the series never silently vanishes.

Peaks come from ``MXTPU_DEVICE_PEAK_FLOPS`` / ``MXTPU_DEVICE_PEAK_HBM_BPS``
when set, else a built-in table keyed on ``jax.devices()[0].device_kind``;
unknown kinds (CPU) fall back to nominal constants and the utilization
numbers become report-only ratios (internally consistent, not meaningful
against real hardware — ``peaks()[2]`` says which).

``capture_profile(seconds)`` is the on-demand ``jax.profiler`` capture
behind ``GET /debug/profile?seconds=N``: single-flight (concurrent
captures get ``ProfileCaptureBusy`` → HTTP 409), bounded output dir
(``MXTPU_PROFILE_KEEP`` newest captures survive).

See docs/OBSERVABILITY.md "Device truth".
"""
from __future__ import annotations

import contextlib
import itertools
import logging
import os
import shutil
import tempfile
import threading
import time as _time

from . import flightrec
from . import watchdog
from .registry import counter, gauge

__all__ = ["program_stats", "peaks", "observe_dispatch", "dispatch_context",
           "start", "stop", "running", "sample_now", "device_memory",
           "set_memory_source", "capture_profile", "ProfileCaptureBusy",
           "capture_in_progress", "dispatch_totals",
           "PEAK_TABLE", "reset_peaks", "HBM_TABLE", "hbm_capacity"]

_LOG = logging.getLogger(__name__)

#: device_kind prefix -> (peak dense FLOP/s at the serving/bench compute
#: dtype (bf16), peak HBM bytes/s). Sources: published TPU spec sheets —
#: the same table bench.py anchored its hand-rolled MFU on, now owned
#: here so every consumer divides by the same denominator.
PEAK_TABLE = {
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e": (197e12, 819e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v5": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e": (918e12, 1640e9),
}

#: report-only fallback for backends not in the table (CPU, unknown
#: accelerators): utilization gauges stay live and internally consistent
#: but are NOT meaningful against hardware peaks (peaks()[2] == "fallback")
_FALLBACK_PEAKS = (1e12, 100e9)

#: device_kind prefix -> per-chip HBM CAPACITY in bytes (spec sheets —
#: the capacity companion of PEAK_TABLE's rate numbers). Consumed by the
#: hlolint H004 gate: an artifact whose header peak_bytes exceeds this
#: is rejected before deploy instead of OOMing after cutover. No
#: fallback entry on purpose — predicting an OOM against a made-up
#: capacity would reject valid programs, so unknown kinds (CPU) return
#: None and the H004 rule skips (MXTPU_HLOLINT_HBM_BUDGET overrides).
HBM_TABLE = {
    "TPU v4i": 8e9,
    "TPU v5 lite": 16e9,
    "TPU v5e": 16e9,
    "TPU v4": 32e9,
    "TPU v5p": 95e9,
    "TPU v5": 95e9,
    "TPU v6 lite": 32e9,
    "TPU v6e": 32e9,
}


def hbm_capacity():
    """(per-chip HBM bytes, source) for this process's backend: the
    HBM_TABLE entry keyed on ``jax.devices()[0].device_kind`` (source
    'table'), or (None, 'unknown') for backends the table doesn't know —
    callers that would otherwise guess (hlolint H004) must skip
    instead."""
    kind = ""
    try:
        import jax
        kind = getattr(jax.devices()[0], "device_kind", "") or ""
    except Exception:
        pass
    # longest prefix wins, so e.g. a v5e chip can never fall through to
    # the broader "TPU v5" entry regardless of table ordering — and a
    # prefix hit only counts at a word boundary: an unlisted sub-variant
    # ("TPU v7x") must come back unknown (H004 skips), never inherit a
    # bigger sibling's capacity and wave a predicted OOM through
    for prefix in sorted(HBM_TABLE, key=len, reverse=True):
        if kind == prefix or (kind.startswith(prefix)
                              and not kind[len(prefix)].isalnum()):
            return float(HBM_TABLE[prefix]), "table"
    return None, "unknown"


# --------------------------------------------------------------- program facts
def program_stats(compiled):
    """Harvest ``{flops, bytes_accessed, peak_bytes, output_bytes}`` from a
    compiled executable's XLA ``cost_analysis()`` + ``memory_analysis()``.

    Returns None when the object is not an analyzable compiled program
    (a lazily-jitted wrapper, a plain python callable) or when both
    analyses come back empty — callers store the result on the AOT cache
    entry at build/load time; NEVER call this per dispatch (mxtpulint
    R001 flags analysis calls in hot paths).
    """
    if not hasattr(compiled, "cost_analysis"):
        return None
    flops = bytes_accessed = 0.0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            flops = float(ca.get("flops") or 0.0)
            bytes_accessed = float(ca.get("bytes accessed") or 0.0)
    except Exception:
        _LOG.debug("cost_analysis failed", exc_info=True)
    peak_bytes = output_bytes = 0.0
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            output_bytes = float(getattr(ma, "output_size_in_bytes", 0) or 0)
            # peak live footprint of one execution: arguments + outputs +
            # compiler temp buffers, minus donated/aliased input bytes
            # (those are reused, not additional)
            peak_bytes = (
                float(getattr(ma, "argument_size_in_bytes", 0) or 0)
                + output_bytes
                + float(getattr(ma, "temp_size_in_bytes", 0) or 0)
                - float(getattr(ma, "alias_size_in_bytes", 0) or 0))
    except Exception:
        _LOG.debug("memory_analysis failed", exc_info=True)
    if flops <= 0.0 and bytes_accessed <= 0.0 and peak_bytes <= 0.0:
        return None
    return {"flops": flops, "bytes_accessed": bytes_accessed,
            "peak_bytes": max(0.0, peak_bytes),
            "output_bytes": output_bytes}


# ------------------------------------------------------------------ peak table
_peaks_lock = threading.Lock()
_peaks = None            # (flops_per_s, hbm_bytes_per_s, source)

_PEAK_FLOPS_G = gauge(
    "mxtpu_device_peak_flops",
    "Per-chip peak FLOP/s the MFU gauges divide by (MXTPU_DEVICE_PEAK_"
    "FLOPS override, else the built-in table keyed on device_kind, else "
    "a report-only fallback — docs/OBSERVABILITY.md 'Device truth').")
_PEAK_BW_G = gauge(
    "mxtpu_device_peak_hbm_bps",
    "Per-chip peak HBM bytes/s the bandwidth-utilization gauges divide "
    "by (MXTPU_DEVICE_PEAK_HBM_BPS override, else the device_kind "
    "table, else a report-only fallback).")


def peaks():
    """(peak_flops_per_s, peak_hbm_bytes_per_s, source) for this process's
    backend; source is 'env' | 'table' | 'fallback'. Resolved once and
    published on the mxtpu_device_peak_* gauges."""
    global _peaks
    if _peaks is not None:
        return _peaks
    with _peaks_lock:
        if _peaks is not None:
            return _peaks
        from .. import config
        env_f = config.get_env("MXTPU_DEVICE_PEAK_FLOPS")
        env_b = config.get_env("MXTPU_DEVICE_PEAK_HBM_BPS")
        kind = ""
        try:
            import jax
            kind = getattr(jax.devices()[0], "device_kind", "") or ""
        except Exception:
            pass
        table = None
        for prefix, vals in PEAK_TABLE.items():
            if kind.startswith(prefix):
                table = vals
                break
        flops_p, bw_p = table if table is not None else _FALLBACK_PEAKS
        base = "table" if table is not None else "fallback"
        if env_f is not None and env_b is not None:
            source = "env"
        elif env_f is not None or env_b is not None:
            # only ONE peak overridden: the other is still `base` — the
            # composite source keeps "fallback" visible so a consumer
            # checking for report-only mode is not lied to
            source = "env+" + base
        else:
            source = base
        if env_f is not None:
            flops_p = float(env_f)
        if env_b is not None:
            bw_p = float(env_b)
        flops_p = max(1.0, float(flops_p))
        bw_p = max(1.0, float(bw_p))
        _PEAK_FLOPS_G.set(flops_p)
        _PEAK_BW_G.set(bw_p)
        _peaks = (flops_p, bw_p, source)
        return _peaks


def reset_peaks():
    """Forget the resolved peaks (tests changing MXTPU_DEVICE_PEAK_*)."""
    global _peaks
    with _peaks_lock:
        _peaks = None


# ------------------------------------------------------- per-dispatch rolling
_MFU = gauge(
    "mxtpu_device_mfu",
    "Rolling (EMA) model-FLOPs utilization per dispatch: the cached "
    "program's cost_analysis FLOPs over the measured block-until-ready "
    "dispatch span, against mxtpu_device_peak_flops. Labels: serving "
    "model (or model digest outside serving), entry kind "
    "(train|eval|serve), data-parallel replica.",
    ("model", "kind", "replica"))
_BW_UTIL = gauge(
    "mxtpu_device_hbm_bw_util",
    "Rolling (EMA) HBM bandwidth utilization per dispatch: the program's "
    "cost_analysis bytes-accessed over the dispatch span, against "
    "mxtpu_device_peak_hbm_bps. High here with low mxtpu_device_mfu "
    "means the program is memory-bound, not compute-bound.",
    ("model", "kind", "replica"))
_FLOPS_TOTAL = counter(
    "mxtpu_device_flops_total",
    "Cost-analysis FLOPs dispatched (sum over instrumented dispatches). "
    "delta(this)/window/mxtpu_device_peak_flops is a scrape window's "
    "achieved MFU — what loadgen stage reports and the devstats CI soak "
    "compute.", ("model", "kind"))
_BYTES_TOTAL = counter(
    "mxtpu_device_bytes_accessed_total",
    "Cost-analysis HBM bytes accessed by instrumented dispatches "
    "(window deltas give achieved bandwidth).", ("model", "kind"))
_DISPATCH_SECONDS = counter(
    "mxtpu_device_dispatch_seconds_total",
    "Measured (block-until-ready) device dispatch seconds — the device "
    "leg of a scrape window, to set against wall-clock for host-overhead "
    "attribution.", ("model", "kind"))
_CHIP_SECONDS = counter(
    "mxtpu_device_chip_seconds_total",
    "Dispatch seconds x participating chips (a K-chip tensor-parallel "
    "program burns K chip-seconds per wall second). "
    "delta(mxtpu_device_flops_total) / delta(this) / "
    "mxtpu_device_peak_flops is a scrape window's achieved PER-CHIP MFU "
    "while executing — exact under any replica/tp topology, which a "
    "wall-window division is not.", ("model", "kind"))

#: EMA smoothing for the rolling gauges: ~last 10 dispatches dominate
_EMA_ALPHA = 0.2
_ema_lock = threading.Lock()
_ema = {}                # (model, kind, replica) -> [mfu, bw]

_ctx = threading.local()


class dispatch_context:
    """Thread-scoped serving context: the batcher worker wraps its
    servable call in ``dispatch_context(model, replica)`` so the MFU
    observation — which happens levels deeper, where the compiled entry
    and its FLOPs are known (EvalStep, ServedModel._run) — is labeled
    with the serving model name and replica index instead of a digest."""

    def __init__(self, model, replica):
        self.model = model
        self.replica = replica

    def __enter__(self):
        self._saved = getattr(_ctx, "value", None)
        _ctx.value = (self.model, self.replica)
        return self

    def __exit__(self, *exc):
        _ctx.value = self._saved


def detach_model(model):
    """Drop one model's rolling per-dispatch gauge series (mxtpu_device_
    mfu / _hbm_bw_util) and their EMA state — the batcher close/unload
    hook, mirroring ServingMetrics.detach_telemetry: a dead model must
    not export its last MFU forever, and hot-reload churn must not grow
    the EMA map without bound. The *_total counters stay (process-
    lifetime cumulative by Prometheus convention)."""
    model = str(model)
    with _ema_lock:
        keys = [k for k in _ema if k[0] == model]
        for k in keys:
            _ema.pop(k, None)
    for m, kind, replica in keys:
        try:
            _MFU.remove(model=m, kind=kind, replica=replica)
            _BW_UTIL.remove(model=m, kind=kind, replica=replica)
        except Exception:
            _LOG.debug("mfu gauge detach failed", exc_info=True)


def in_dispatch_context():
    """True on a batcher worker thread inside dispatch_context — the
    serving path, where a block-until-ready observation moves cost
    instead of adding any (jit.EvalStep gates its sync on this)."""
    return getattr(_ctx, "value", None) is not None


def observe_dispatch(kind, stats, dur_s, model=None, replica=None,
                     devices=1):
    """Record one measured dispatch of a program with known ``stats``
    (the aot.CACHE entry's program_stats dict). ``dur_s`` is the
    block-until-ready span the caller measured; ``devices`` is how many
    chips executed the program (a tensor-parallel group passes its mesh
    size — the program's cost-analysis FLOPs are spread over all of
    them, so dividing by ONE chip's peak would overstate MFU by the
    group size). An ambient dispatch_context (the batcher worker's
    serving model name) WINS over the caller's ``model`` — the caller
    passes its model digest as the fallback label for dispatches outside
    serving. Never raises into the hot path; a dropped observation is
    debug-logged (R005 discipline)."""
    if not stats or dur_s <= 0.0:
        return
    try:
        ctx = getattr(_ctx, "value", None)
        if ctx is not None:
            model = ctx[0]
            if replica is None:
                replica = ctx[1]
        model = str(model if model is not None else "-")
        replica = int(replica or 0)
        devices = max(1, int(devices))
        flops_p, bw_p, _src = peaks()
        flops = float(stats.get("flops") or 0.0)
        nbytes = float(stats.get("bytes_accessed") or 0.0)
        mfu = flops / dur_s / (flops_p * devices)
        bw = nbytes / dur_s / (bw_p * devices)
        key = (model, str(kind), replica)
        with _ema_lock:
            cur = _ema.get(key)
            if cur is None:
                cur = _ema[key] = [mfu, bw]
            else:
                cur[0] += _EMA_ALPHA * (mfu - cur[0])
                cur[1] += _EMA_ALPHA * (bw - cur[1])
            mfu_s, bw_s = cur
        _MFU.set(mfu_s, model=model, kind=kind, replica=replica)
        _BW_UTIL.set(bw_s, model=model, kind=kind, replica=replica)
        _FLOPS_TOTAL.inc(flops, model=model, kind=kind)
        _BYTES_TOTAL.inc(nbytes, model=model, kind=kind)
        _DISPATCH_SECONDS.inc(dur_s, model=model, kind=kind)
        _CHIP_SECONDS.inc(dur_s * devices, model=model, kind=kind)
    except Exception:
        _LOG.debug("devstats dispatch observation dropped", exc_info=True)


# ------------------------------------------------------------- HBM sampler
_MEMORY_BYTES = gauge(
    "mxtpu_device_memory_bytes",
    "Live device memory sampled by the devstats daemon from PJRT "
    "device.memory_stats() (stats: bytes_in_use, peak_bytes_in_use, "
    "bytes_limit). Backends reporting no memory stats (CPU) degrade to "
    "host-RSS report-only samples under device='host' (stats: rss_bytes, "
    "peak_rss_bytes). >90% of bytes_limit files a flightrec "
    "hbm_pressure event once per episode.", ("device", "stat"))

_MEM_STATS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
#: pressure episode hysteresis: fire at >90% of bytes_limit, re-arm <85%
_PRESSURE_HIGH = 0.90
_PRESSURE_LOW = 0.85

_mem_lock = threading.Lock()
_mem_source = None       # injectable: fn() -> {device: {stat: bytes}}
_last_snapshot = {}
_published_series = set()          # (device, stat) pairs set on the gauge
_pressured = set()                 # devices currently in a pressure episode
#: gauge publishing happens ONLY between start() and stop() (guarded by
#: _mem_lock): a passive device_memory()/profiler read after stop() must
#: not resurrect mxtpu_device_memory_bytes series nobody will ever
#: refresh or detach again
_session_active = False
_sampler_lock = threading.Lock()   # sampler lifecycle
_sampler_thread = None
_sampler_stop = None
_HB_CHANNEL = "devstats"


def set_memory_source(fn):
    """Override where memory samples come from: ``fn() -> {device_name:
    {stat_name: bytes}}`` (tests; backends with out-of-band memory
    telemetry). None restores the PJRT default."""
    global _mem_source
    with _mem_lock:
        _mem_source = fn


def _host_rss():
    """Report-only host fallback so the memory series never silently
    vanishes on backends whose PJRT client reports nothing (CPU)."""
    import sys
    out = {}
    try:
        import resource
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss unit is platform-defined: kilobytes on Linux/BSD,
        # BYTES on macOS — scaling unconditionally would report 1024x
        out["peak_rss_bytes"] = int(peak) * (
            1 if sys.platform == "darwin" else 1024)
    except Exception:
        pass
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        out["rss_bytes"] = pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        if "peak_rss_bytes" in out:
            out["rss_bytes"] = out["peak_rss_bytes"]
    return {"host": out} if out else {}


def _collect():
    with _mem_lock:
        src = _mem_source
    if src is not None:
        try:
            snap = src() or {}
            return {str(d): {str(k): int(v) for k, v in s.items()}
                    for d, s in snap.items()}
        except Exception:
            _LOG.debug("injected memory source failed", exc_info=True)
            return {}
    out = {}
    try:
        import jax
        for d in jax.local_devices():
            try:
                s = d.memory_stats() or {}
            except Exception:
                s = {}
            entry = {k: int(s[k]) for k in _MEM_STATS if k in s}
            if entry:
                out[str(d)] = entry
    except Exception:
        _LOG.debug("device memory sample failed", exc_info=True)
    if not out:
        out = _host_rss()
    return out


def sample_now():
    """One sampler tick, callable without the daemon: poll the memory
    source live, run the pressure check, and return the
    {device: {stat: bytes}} snapshot. The mxtpu_device_memory_bytes
    gauges are published only while a sampler session is active (between
    start() and stop()) — a passive read outside it must not leave
    frozen series on the exposition."""
    global _last_snapshot
    snap = _collect()
    with _mem_lock:
        publish = _session_active
    for dev, stats in snap.items():
        if publish:
            for stat, val in stats.items():
                try:
                    with _mem_lock:
                        # re-check under the lock: a concurrent stop()
                        # must not race a publish past its detach sweep
                        if _session_active:
                            _MEMORY_BYTES.set(val, device=dev, stat=stat)
                            _published_series.add((dev, stat))
                except Exception:
                    _LOG.debug("memory gauge update dropped",
                               exc_info=True)
        limit = stats.get("bytes_limit")
        used = stats.get("bytes_in_use")
        if limit and used is not None:
            frac = used / float(limit)
            with _mem_lock:
                in_episode = dev in _pressured
                if frac > _PRESSURE_HIGH and not in_episode:
                    _pressured.add(dev)
                    fire = True
                else:
                    fire = False
                    if frac < _PRESSURE_LOW and in_episode:
                        _pressured.discard(dev)
            if fire:
                flightrec.record("hbm_pressure", device=dev,
                                 frac=round(frac, 4), bytes_in_use=used,
                                 bytes_limit=limit)
                _LOG.warning("device %s HBM pressure: %.1f%% of limit "
                             "(%d / %d bytes)", dev, 100 * frac, used,
                             limit)
    with _mem_lock:
        _last_snapshot = snap
    return snap


def device_memory():
    """The newest sampler snapshot (stable keys: bytes_in_use /
    peak_bytes_in_use / bytes_limit per device; rss fallback keys under
    'host'). Samples on demand when the daemon is not running, but keeps
    serving the last-known snapshot if a live sample fails — this is the
    delegate behind profiler.device_memory()."""
    if not running():
        try:
            return sample_now()
        except Exception:
            _LOG.debug("on-demand memory sample failed", exc_info=True)
    with _mem_lock:
        return {d: dict(s) for d, s in _last_snapshot.items()}


def _poll(stop, poll_s):
    while not stop.wait(poll_s):
        watchdog.heartbeat(_HB_CHANNEL)
        try:
            sample_now()
        except Exception:
            # the sampler must outlive whatever it samples; the skipped
            # tick stays debug-visible (R005)
            _LOG.debug("devstats sampler tick failed", exc_info=True)


def start(poll_s=None):
    """Start (or restart with new settings) the HBM sampler daemon.
    Heartbeat-registered on the 'devstats' watchdog channel; autostarted
    at package import when MXTPU_DEVSTATS=1. Returns the thread."""
    from .. import config
    global _sampler_thread, _sampler_stop
    if poll_s is None:
        poll_s = config.get_env("MXTPU_DEVSTATS_POLL_S")
    poll_s = max(0.01, float(poll_s))
    global _session_active
    with _sampler_lock:
        _stop_locked()
        watchdog.register(_HB_CHANNEL, quiet_s=max(60.0, poll_s * 10))
        with _mem_lock:
            _session_active = True
        # first sample SYNCHRONOUSLY, before the daemon exists: a
        # device_memory() call right after start() must see a live
        # snapshot, not an empty one that only fills after the first
        # poll tick
        try:
            sample_now()
        except Exception:
            _LOG.debug("initial devstats sample failed", exc_info=True)
        stop_ev = threading.Event()
        t = threading.Thread(target=_poll, args=(stop_ev, poll_s),
                             daemon=True, name="mxtpu-devstats")
        _sampler_stop, _sampler_thread = stop_ev, t
        t.start()
    return t


def _stop_locked():
    """Signal + join the sampler and DETACH its state: the heartbeat
    channel is unregistered (silence from a stopped sampler is not a
    stall) and every memory series it published is removed (a stopped
    sampler must not export frozen bytes forever). Caller holds
    _sampler_lock."""
    global _sampler_thread, _sampler_stop, _session_active
    stop_ev, t = _sampler_stop, _sampler_thread
    _sampler_stop = _sampler_thread = None
    if stop_ev is not None:
        stop_ev.set()
        if t is not None:
            t.join(timeout=5.0)
        watchdog.unregister(_HB_CHANNEL)
        # end the session BEFORE the detach sweep: any sample racing the
        # stop re-checks _session_active under _mem_lock and cannot
        # publish after (and so escape) the sweep
        with _mem_lock:
            _session_active = False
            series = list(_published_series)
            _published_series.clear()
        for dev, stat in series:
            try:
                _MEMORY_BYTES.remove(device=dev, stat=stat)
            except Exception:
                _LOG.debug("memory gauge detach failed", exc_info=True)


def stop():
    with _sampler_lock:
        _stop_locked()


def running():
    t = _sampler_thread
    return t is not None and t.is_alive()


# ----------------------------------------------------------- profile capture
class ProfileCaptureBusy(RuntimeError):
    """A jax.profiler capture is already in flight (HTTP 409)."""


_capture_lock = threading.Lock()
_capture_seq = itertools.count(1)


def _capture_base(out_dir=None):
    from .. import config
    base = out_dir or config.get_env("MXTPU_PROFILE_DIR")
    if not base:
        base = os.path.join(tempfile.gettempdir(), "mxtpu_profile")
    return base


def _prune_mtime(path):
    """Missing-file-tolerant sort key: a capture subdir can be deleted
    (concurrent prune in another process, operator rm) between
    os.listdir and the sort's getmtime — a vanished dir sorts oldest and
    its rmtree below is already an ignore_errors no-op."""
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def _prune(base, keep):
    """Bound the capture dir: keep the ``keep`` newest capture subdirs."""
    try:
        subdirs = [os.path.join(base, d) for d in os.listdir(base)
                   if d.startswith("capture-")]
        subdirs.sort(key=_prune_mtime)
        for victim in subdirs[:max(0, len(subdirs) - keep)]:
            shutil.rmtree(victim, ignore_errors=True)
    except Exception:
        _LOG.debug("profile dir prune failed", exc_info=True)


@contextlib.contextmanager
def _trace_session(path):
    """One profiler capture into ``path``, python tracer OFF by default.

    The python tracer instruments every interpreter call while tracing
    — measured ~30% on a timer-bound serving request — and that tax
    lands squarely on p99 whenever a capture overlaps traffic (the
    continuous profstats daemon's whole operating mode). The op-level
    attribution layer only reads the XLA TraceMe events (host_tracer),
    which survive with the python tracer off, so off is the default;
    MXTPU_PROFILE_PYTHON_TRACER=1 re-enables python frames for
    interactive debugging. Falls back to jax.profiler.start_trace when
    the jaxlib session API is unavailable."""
    from .. import config
    import jax
    sess = None
    try:
        from jax._src.lib import xla_client
        jax.devices()                    # backends must exist first
        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = (
            1 if config.get_env("MXTPU_PROFILE_PYTHON_TRACER") else 0)
        sess = xla_client.profiler.ProfilerSession(opts)
    except Exception:
        _LOG.debug("low-overhead profiler session unavailable; falling "
                   "back to jax.profiler.start_trace", exc_info=True)
    if sess is None:
        jax.profiler.start_trace(path)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
        return
    try:
        yield
    finally:
        sess.export(sess.stop(), path)


def capture_profile(seconds=2.0, out_dir=None):
    """On-demand ``jax.profiler`` capture (GET /debug/profile?seconds=N):
    trace into a fresh subdir of MXTPU_PROFILE_DIR for ``seconds``
    (clamped to MXTPU_PROFILE_MAX_S), then prune the dir down to
    MXTPU_PROFILE_KEEP captures. Single-flight: a concurrent call raises
    ProfileCaptureBusy instead of corrupting the in-flight trace (the
    HTTP route maps it to 409)."""
    from .. import config
    if _capture_lock.acquire(blocking=False):
        try:
            max_s = float(config.get_env("MXTPU_PROFILE_MAX_S"))
            seconds = min(max(0.05, float(seconds)), max(0.05, max_s))
            base = _capture_base(out_dir)
            path = os.path.join(base, "capture-%d-%d"
                                % (os.getpid(), next(_capture_seq)))
            os.makedirs(path, exist_ok=True)
            with _trace_session(path):
                _time.sleep(seconds)
            _prune(base, int(config.get_env("MXTPU_PROFILE_KEEP")))
            # capture_id = the subdir basename: stable across _prune (a
            # remembered profstats summary under this id outlives the
            # dir), unique per process+sequence
            return {"dir": path, "seconds": seconds,
                    "capture_id": os.path.basename(path)}
        finally:
            _capture_lock.release()
    raise ProfileCaptureBusy(
        "a profiler capture is already in progress (single-flight: "
        "retry after it finishes)")


def capture_in_progress():
    """True while capture_profile holds the single-flight lock."""
    if _capture_lock.acquire(blocking=False):
        try:
            return False
        finally:
            _capture_lock.release()
    return True


def dispatch_totals():
    """Process-cumulative dispatch facts summed over every (model, kind)
    series — the before/after snapshot pair profstats subtracts to join
    a capture window against device truth: {"flops", "bytes",
    "dispatch_s", "chip_s", "by_model": {model: dispatch_s}}."""
    out = {"flops": 0.0, "bytes": 0.0, "dispatch_s": 0.0, "chip_s": 0.0,
           "by_model": {}}
    for metric, key in ((_FLOPS_TOTAL, "flops"), (_BYTES_TOTAL, "bytes"),
                        (_DISPATCH_SECONDS, "dispatch_s"),
                        (_CHIP_SECONDS, "chip_s")):
        for labels, v in metric.series():
            out[key] += v
            if key == "dispatch_s":
                m = labels.get("model", "-")
                out["by_model"][m] = out["by_model"].get(m, 0.0) + v
    return out
