"""Symbolic control-flow (ref python/mxnet/symbol/contrib.py:92 foreach,
:340 while_loop, :566 cond; lowered via src/operator/control_flow.cc).

The body/cond/func callables are invoked ONCE at graph-construction time on
placeholder Variables to capture the loop subgraph (the analog of the
reference's subgraph cut + CachedOp). Free variables of the subgraph —
closed-over parameter symbols — are lifted into inputs of the control-flow
node, so gradients flow to them when the bound executor differentiates.
Execution delegates to ndarray.contrib (Python loop eagerly, lax.scan /
masked-scan / lax.cond under tracing)."""
from __future__ import annotations

from .symbol import Symbol, Group, var, _auto_name
from ..ndarray import contrib as ndc

__all__ = ["foreach", "while_loop", "cond"]


def _subgraph(build, ph_names):
    """Run the builder on placeholders, return (out_syms, free_var_syms)."""
    outs = build()
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    g = Group(outs)
    var_nodes = {s.name: s for s in g.get_internals() if s.is_var}
    free = [var_nodes[n] for n in g.list_arguments() if n not in ph_names]
    return outs, free


def foreach(body, data, init_states):
    """body(data_sym, state_syms) -> (out, states). Returns (outs, states)."""
    data_list = list(data) if isinstance(data, (list, tuple)) else [data]
    states_list = list(init_states)
    ph_d = [var(_auto_name("foreach_data")) for _ in data_list]
    ph_s = [var(_auto_name("foreach_state")) for _ in states_list]
    ph_names = {p.name for p in ph_d + ph_s}

    box = {}

    def build():
        d_arg = ph_d if isinstance(data, (list, tuple)) else ph_d[0]
        out, new_states = body(d_arg, ph_s)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        box["n_out"] = len(outs)
        box["out_is_list"] = isinstance(out, (list, tuple))
        return outs + list(new_states)

    all_outs, free = _subgraph(build, ph_names)
    n_out, n_state = box["n_out"], len(states_list)
    sub = Group(all_outs)

    def op(*arrs):
        d = list(arrs[:len(data_list)])
        s = list(arrs[len(data_list):len(data_list) + n_state])
        extras = list(arrs[len(data_list) + n_state:])

        def nd_body(d_i, st):
            d_i = d_i if isinstance(d_i, list) else [d_i]
            bind = dict(zip([p.name for p in ph_d], d_i))
            bind.update(zip([p.name for p in ph_s], st))
            bind.update(zip([f.name for f in free], extras))
            cache = {}  # shared: nodes reused by several outputs run once
            res = [o.eval_imperative(bind, _cache=cache) for o in all_outs]
            out = res[:n_out] if box["out_is_list"] else res[0]
            return out, res[n_out:]

        d_arg = d if len(d) > 1 or isinstance(data, (list, tuple)) else d[0]
        outs, states = ndc.foreach(nd_body, d_arg, s)
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        res = outs + list(states)
        return res[0] if len(res) == 1 else res

    node = Symbol(op=op, op_name="_foreach",
                  inputs=data_list + states_list + free,
                  num_outputs=n_out + n_state)
    outs = [node[i] for i in range(n_out)]
    states = [node[n_out + i] for i in range(n_state)]
    return (outs if box["out_is_list"] else outs[0]), states


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """cond(*vars) -> scalar sym; func(*vars) -> (step_out, new_vars)."""
    loop_vars = list(loop_vars)
    ph_v = [var(_auto_name("while_var")) for _ in loop_vars]
    ph_names = {p.name for p in ph_v}

    box = {}

    def build():
        pred = cond_fn(*ph_v)
        out, new_vars = func(*ph_v)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        box["n_out"] = len(outs)
        box["out_is_list"] = isinstance(out, (list, tuple))
        return [pred] + outs + list(new_vars)

    all_outs, free = _subgraph(build, ph_names)
    n_out, n_var = box["n_out"], len(loop_vars)
    pred_sym, out_syms = all_outs[0], all_outs[1:1 + n_out]
    var_syms = all_outs[1 + n_out:]

    def op(*arrs):
        vs = list(arrs[:n_var])
        extras = list(arrs[n_var:])

        def bindings(vals):
            b = dict(zip([p.name for p in ph_v], vals))
            b.update(zip([f.name for f in free], extras))
            return b

        def nd_cond(*vals):
            return pred_sym.eval_imperative(bindings(list(vals)))

        def nd_func(*vals):
            b = bindings(list(vals))
            cache = {}
            outs = [o.eval_imperative(b, _cache=cache) for o in out_syms]
            new_vars = [v.eval_imperative(b, _cache=cache) for v in var_syms]
            out = outs if box["out_is_list"] else outs[0]
            return out, new_vars

        outs, final_vars = ndc.while_loop(nd_cond, nd_func, vs,
                                          max_iterations=max_iterations)
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        res = outs + list(final_vars)
        return res[0] if len(res) == 1 else res

    node = Symbol(op=op, op_name="_while_loop", inputs=loop_vars + free,
                  num_outputs=n_out + n_var)
    outs = [node[i] for i in range(n_out)]
    finals = [node[n_out + i] for i in range(n_var)]
    return (outs if box["out_is_list"] else outs[0]), finals


def cond(pred, then_func, else_func):
    """pred: scalar Symbol; then/else: () -> Symbol or list of Symbols."""
    box = {}

    def build_then():
        out = then_func()
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        box["n_out"] = len(outs)
        box["is_list"] = isinstance(out, (list, tuple))
        return outs

    then_outs, then_free = _subgraph(build_then, set())
    else_outs, else_free = _subgraph(
        lambda: else_func(), set())
    if len(else_outs) != box["n_out"]:
        raise ValueError("cond branches must produce the same number of "
                         "outputs (%d vs %d)" % (box["n_out"], len(else_outs)))
    # dedupe free vars across branches by name
    free, seen = [], set()
    for f in then_free + else_free:
        if f.name not in seen:
            seen.add(f.name)
            free.append(f)
    n_out = box["n_out"]

    def op(pred_arr, *extras):
        bind = dict(zip([f.name for f in free], extras))

        def _branch(outs_syms):
            def run():
                cache = {}
                res = [o.eval_imperative(dict(bind), _cache=cache)
                       for o in outs_syms]
                return res if box["is_list"] else res[0]
            return run

        out = ndc.cond(pred_arr, _branch(then_outs), _branch(else_outs))
        if n_out == 1:
            return out[0] if isinstance(out, (list, tuple)) else out
        return list(out) if isinstance(out, (list, tuple)) else [out]

    node = Symbol(op=op, op_name="_cond", inputs=[pred] + free,
                  num_outputs=n_out)
    if n_out == 1:
        return node
    outs = [node[i] for i in range(n_out)]
    return outs if box["is_list"] else outs[0]
