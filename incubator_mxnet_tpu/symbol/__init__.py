"""mx.sym namespace — symbolic mirrors of the nd ops
(ref python/mxnet/symbol/__init__.py and register.py generation).

Simple ops are generated from the nd namespace; layer ops (FullyConnected,
Convolution, ...) auto-create parameter Variables with deferred shape rules,
so ``simple_bind`` can allocate them from the data shape alone — the analog of
NNVM shape inference (SURVEY §2.1 GraphExecutor InferShape)."""
from __future__ import annotations

import numpy as onp

from .. import ndarray as nd
from .symbol import (Symbol, Group, Variable, var, load, load_json, zeros, ones,
                     _auto_name)

__all__ = ["Symbol", "Group", "Variable", "var", "load", "load_json", "zeros",
           "ones"]

_OP_TABLE = {}


def _deferred_rules(op_name, kwargs):
    """Deferred param-shape rules by op + attrs, for graph-JSON reload
    (input index → shape_fn(data_shape))."""
    if op_name == "FullyConnected":
        nh = kwargs.get("num_hidden")
        flatten_ = kwargs.get("flatten", True)

        def w_shape(s):
            inu = int(onp.prod(s[1:])) if flatten_ else s[-1]
            return (nh, inu)
        return {1: w_shape, 2: lambda s: (nh,)}
    if op_name == "Convolution":
        nf = kwargs.get("num_filter")
        kernel = tuple(kwargs.get("kernel"))
        ng = kwargs.get("num_group", 1)
        return {1: lambda s: (nf, s[1] // ng) + kernel, 2: lambda s: (nf,)}
    if op_name in ("BatchNorm",):
        ax = kwargs.get("axis", 1)
        c = lambda s: (s[ax],)
        return {1: c, 2: c, 3: c, 4: c}
    if op_name == "LayerNorm":
        ax = kwargs.get("axis", -1)
        c = lambda s: (s[ax],)
        return {1: c, 2: c}
    if op_name == "Embedding":
        return {1: lambda s: (kwargs.get("input_dim"), kwargs.get("output_dim"))}
    if op_name == "Deconvolution":
        nf = kwargs.get("num_filter")
        kernel = tuple(kwargs.get("kernel"))
        ng = kwargs.get("num_group", 1)
        return {1: lambda s: (s[1], nf // ng) + kernel, 2: lambda s: (nf,)}
    if op_name in ("GroupNorm", "InstanceNorm"):
        c = lambda s: (s[1],)
        return {1: c, 2: c}
    return None


def _op_lookup(name):
    if name in _OP_TABLE:
        return _OP_TABLE[name]
    return getattr(nd, name)


def _flat_adapter(fn, spec):
    """Rebuild list-of-array positional args from the flattened Symbol
    inputs: spec[i] is None for a plain arg, or the list length. The spec
    travels in kwargs as ``__arg_spec__`` so graph JSON round-trips."""
    def call(*vals, **kw):
        kw.pop("__arg_spec__", None)
        it = iter(vals)
        rebuilt = []
        for s in spec:
            if s is None:
                rebuilt.append(next(it))
            elif s == "N":          # an omitted optional input (None)
                rebuilt.append(None)
            else:
                rebuilt.append([next(it) for _ in range(s)])
        return fn(*rebuilt, **kw)
    return call


def _symbolize(fn, op_name):
    """Wrap an nd function into a Symbol builder (≙ the reference's
    register.py code-gen: ONE registry drives both namespaces —
    ref python/mxnet/symbol/register.py:1, ndarray/register.py:265)."""

    def sym_fn(*args, name=None, **kwargs):
        inputs, spec = [], []
        for a in args:
            if isinstance(a, Symbol):
                inputs.append(a)
                spec.append(None)
            elif a is None:         # absent optional input (e.g. RNN state)
                spec.append("N")
            elif isinstance(a, (list, tuple)) and a and \
                    all(isinstance(x, Symbol) for x in a):
                inputs.extend(a)
                spec.append(len(a))
            else:
                raise TypeError("%s: positional args must be Symbols "
                                "(or lists of Symbols)" % op_name)
        if any(s is not None for s in spec):
            kwargs["__arg_spec__"] = tuple(spec)
            op = _flat_adapter(fn, spec)
        else:
            op = fn
        return Symbol(op=op, op_name=op_name, inputs=inputs, kwargs=kwargs,
                      name=name)

    sym_fn.__name__ = op_name
    _OP_TABLE[op_name] = fn
    return sym_fn


# ---------------------------------------------------- registry unification
# ONE registry drives both namespaces (the reference generates nd and sym
# from the same op registry — python/mxnet/symbol/register.py:1,
# ndarray/register.py:265). Every public nd callable that is not in the
# documented exclusion table below is symbolized automatically, so adding
# an nd op can never silently widen the nd/sym gap again. Layer ops with
# auto-created parameter Variables (FullyConnected, Convolution, ...) are
# re-defined further down and override their plain auto-symbolized forms.
_SYM_EXCLUDE = {
    # host-side constructors / serialization / interop — these have no
    # graph-node semantics (a Symbol is built from var() + operators)
    "array": "host constructor; use sym.var + bind",
    "empty": "uninitialized host constructor",
    "save": "file io (Symbol.save writes graph JSON instead)",
    "load": "file io (sym.load reads graph JSON instead)",
    "from_dlpack": "zero-copy interop is eager-only",
    "from_numpy": "zero-copy interop is eager-only",
    "to_dlpack_for_read": "zero-copy interop is eager-only",
    "to_dlpack_for_write": "zero-copy interop is eager-only",
    "load_frombuffer": "file io",
    "imdecode": "host-side jpeg decode (io pipeline, not an operator)",
    "waitall": "engine sync primitive, not an operator",
    "rnn_param_size": "shape helper returning a python int",
}

_g = globals()


def _auto_register_from_nd():
    from ..base import public_op_names
    added = []
    for _n in public_op_names(nd, exclude=_SYM_EXCLUDE):
        if _n in _g:
            continue
        _g[_n] = _symbolize(getattr(nd, _n), _n)
        added.append(_n)
    return added


__all__ += _auto_register_from_nd()

# operator-sugar node names (Symbol.__add__ etc., symbol.py _binop) so
# graph JSON containing them reloads; the *_scalar variants resolve through
# the kwargs-driven impls in symbol.py
from .symbol import _scalar_binop_fn as _sbf  # noqa: E402

for _opname, _fn in [("_plus", nd.add), ("_minus", nd.subtract),
                     ("_mul", nd.multiply), ("_div", nd.divide),
                     ("_pow", nd.power), ("_greater", nd.greater),
                     ("_greater_equal", nd.greater_equal),
                     ("_lesser", nd.lesser), ("_lesser_equal", nd.lesser_equal),
                     ("_mod", nd.modulo)]:
    _OP_TABLE[_opname] = _fn
    _OP_TABLE[_opname + "_scalar"] = _sbf(_fn)
_OP_TABLE["negative"] = nd.negative
Concat = _g["concat"]
SliceChannel = _g["split"]
Flatten = _g["flatten"]
Cast = _g["cast"]


# -------------------------------------------------------------- layer ops
def _param_var(base_name, suffix, shape_fn):
    v = var("%s_%s" % (base_name, suffix))
    v._deferred_shape_fn = shape_fn
    v._is_param = True
    return v


def FullyConnected(data=None, weight=None, bias=None, num_hidden=None,
                   no_bias=False, flatten=True, name=None, **kw):
    """ref nn/fully_connected.cc symbol interface (auto weight/bias vars)."""
    name = name or _auto_name("fullyconnected")

    def w_shape(in_shape):
        in_units = int(onp.prod(in_shape[1:])) if flatten else in_shape[-1]
        return (num_hidden, in_units)

    weight = weight if weight is not None else _param_var(name, "weight", w_shape)
    inputs = [data, weight]
    if not no_bias:
        bias = bias if bias is not None else _param_var(
            name, "bias", lambda s: (num_hidden,))
        inputs.append(bias)
    kwargs = dict(num_hidden=num_hidden, no_bias=no_bias, flatten=flatten)
    return Symbol(op=nd.FullyConnected, op_name="FullyConnected", inputs=inputs,
                  kwargs=kwargs, name=name)


def Convolution(data=None, weight=None, bias=None, kernel=None, stride=(1, 1),
                dilate=(1, 1), pad=(0, 0), num_filter=None, num_group=1,
                no_bias=False, layout="NCHW", name=None, **kw):
    name = name or _auto_name("convolution")

    def w_shape(in_shape):
        return (num_filter, in_shape[1] // num_group) + tuple(kernel)

    weight = weight if weight is not None else _param_var(name, "weight", w_shape)
    inputs = [data, weight]
    if not no_bias:
        bias = bias if bias is not None else _param_var(
            name, "bias", lambda s: (num_filter,))
        inputs.append(bias)
    kwargs = dict(kernel=kernel, stride=stride, dilate=dilate, pad=pad,
                  num_filter=num_filter, num_group=num_group, no_bias=no_bias)
    return Symbol(op=nd.Convolution, op_name="Convolution", inputs=inputs,
                  kwargs=kwargs, name=name)


def BatchNorm(data=None, gamma=None, beta=None, moving_mean=None, moving_var=None,
              eps=1e-5, momentum=0.9, fix_gamma=True, use_global_stats=False,
              axis=1, name=None, **kw):
    name = name or _auto_name("batchnorm")
    c_shape = lambda s: (s[axis],)
    gamma = gamma if gamma is not None else _param_var(name, "gamma", c_shape)
    beta = beta if beta is not None else _param_var(name, "beta", c_shape)
    moving_mean = moving_mean if moving_mean is not None else _param_var(
        name, "moving_mean", c_shape)
    moving_var = moving_var if moving_var is not None else _param_var(
        name, "moving_var", c_shape)
    moving_mean._is_aux = True
    moving_var._is_aux = True
    kwargs = dict(eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                  use_global_stats=use_global_stats, axis=axis)
    return Symbol(op=nd.BatchNorm, op_name="BatchNorm",
                  inputs=[data, gamma, beta, moving_mean, moving_var],
                  kwargs=kwargs, name=name)


def Activation(data=None, act_type="relu", name=None, **kw):
    return Symbol(op=nd.Activation, op_name="Activation", inputs=[data],
                  kwargs=dict(act_type=act_type), name=name)


def LeakyReLU(data=None, act_type="leaky", slope=0.25, name=None, **kw):
    return Symbol(op=nd.LeakyReLU, op_name="LeakyReLU", inputs=[data],
                  kwargs=dict(act_type=act_type, slope=slope), name=name)


def Pooling(data=None, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid", name=None, **kw):
    kwargs = dict(kernel=kernel, pool_type=pool_type, global_pool=global_pool,
                  stride=stride, pad=pad, pooling_convention=pooling_convention)
    return Symbol(op=nd.Pooling, op_name="Pooling", inputs=[data], kwargs=kwargs,
                  name=name)


def Dropout(data=None, p=0.5, name=None, **kw):
    return Symbol(op=nd.Dropout, op_name="Dropout", inputs=[data],
                  kwargs=dict(p=p), name=name)


def SoftmaxOutput(data=None, label=None, grad_scale=1.0, name=None, **kw):
    name = name or "softmax"
    label = label if label is not None else var(name + "_label")
    label._is_label = True
    return Symbol(op=nd.SoftmaxOutput, op_name="SoftmaxOutput",
                  inputs=[data, label], kwargs=dict(grad_scale=grad_scale),
                  name=name)


def Embedding(data=None, weight=None, input_dim=None, output_dim=None,
              name=None, **kw):
    name = name or _auto_name("embedding")
    weight = weight if weight is not None else _param_var(
        name, "weight", lambda s: (input_dim, output_dim))
    return Symbol(op=nd.Embedding, op_name="Embedding", inputs=[data, weight],
                  kwargs=dict(input_dim=input_dim, output_dim=output_dim),
                  name=name)


def LayerNorm(data=None, gamma=None, beta=None, axis=-1, eps=1e-5, name=None, **kw):
    name = name or _auto_name("layernorm")
    c_shape = lambda s: (s[axis],)
    gamma = gamma if gamma is not None else _param_var(name, "gamma", c_shape)
    beta = beta if beta is not None else _param_var(name, "beta", c_shape)
    return Symbol(op=nd.LayerNorm, op_name="LayerNorm",
                  inputs=[data, gamma, beta], kwargs=dict(axis=axis, eps=eps),
                  name=name)


def Deconvolution(data=None, weight=None, bias=None, kernel=None, stride=(1, 1),
                  dilate=(1, 1), pad=(0, 0), adj=(0, 0), num_filter=None,
                  num_group=1, no_bias=False, target_shape=None, name=None, **kw):
    """ref nn/deconvolution-inl.h symbol interface; weight is
    (in_channels, num_filter/num_group, *kernel)."""
    name = name or _auto_name("deconvolution")

    def w_shape(in_shape):
        return (in_shape[1], num_filter // num_group) + tuple(kernel)

    weight = weight if weight is not None else _param_var(name, "weight", w_shape)
    inputs = [data, weight]
    if not no_bias:
        bias = bias if bias is not None else _param_var(
            name, "bias", lambda s: (num_filter,))
        inputs.append(bias)
    kwargs = dict(kernel=kernel, stride=stride, dilate=dilate, pad=pad, adj=adj,
                  num_filter=num_filter, num_group=num_group, no_bias=no_bias,
                  target_shape=target_shape)
    return Symbol(op=nd.Deconvolution, op_name="Deconvolution", inputs=inputs,
                  kwargs=kwargs, name=name)


def GroupNorm(data=None, gamma=None, beta=None, num_groups=1, eps=1e-5,
              name=None, **kw):
    name = name or _auto_name("groupnorm")
    c_shape = lambda s: (s[1],)
    gamma = gamma if gamma is not None else _param_var(name, "gamma", c_shape)
    beta = beta if beta is not None else _param_var(name, "beta", c_shape)
    return Symbol(op=nd.GroupNorm, op_name="GroupNorm",
                  inputs=[data, gamma, beta],
                  kwargs=dict(num_groups=num_groups, eps=eps), name=name)


def InstanceNorm(data=None, gamma=None, beta=None, eps=1e-3, name=None, **kw):
    name = name or _auto_name("instancenorm")
    c_shape = lambda s: (s[1],)
    gamma = gamma if gamma is not None else _param_var(name, "gamma", c_shape)
    beta = beta if beta is not None else _param_var(name, "beta", c_shape)
    return Symbol(op=nd.InstanceNorm, op_name="InstanceNorm",
                  inputs=[data, gamma, beta], kwargs=dict(eps=eps), name=name)


def _make_regression_output(op_name, nd_fn):
    def builder(data=None, label=None, grad_scale=1.0, name=None, **kw):
        name = name or _auto_name(op_name.lower())
        label = label if label is not None else var(name + "_label")
        label._is_label = True
        return Symbol(op=nd_fn, op_name=op_name, inputs=[data, label],
                      kwargs=dict(grad_scale=grad_scale), name=name)
    builder.__name__ = op_name
    return builder


LinearRegressionOutput = _make_regression_output(
    "LinearRegressionOutput", nd.LinearRegressionOutput)
LogisticRegressionOutput = _make_regression_output(
    "LogisticRegressionOutput", nd.LogisticRegressionOutput)
MAERegressionOutput = _make_regression_output(
    "MAERegressionOutput", nd.MAERegressionOutput)


for _n in ["FullyConnected", "Convolution", "BatchNorm", "Activation", "LeakyReLU",
           "Pooling", "Dropout", "SoftmaxOutput", "Embedding", "LayerNorm",
           "LinearRegressionOutput", "Deconvolution", "GroupNorm",
           "InstanceNorm"]:
    __all__.append(_n)
    _OP_TABLE[_n] = getattr(nd, _n, None)

# backend-alias layer ops resolve to the param-creating builders, exactly
# as the reference maps the *_v1 / cudnn names onto the same operators
BatchNorm_v1 = CuDNNBatchNorm = BatchNorm
Convolution_v1 = Convolution
Pooling_v1 = Pooling

from . import contrib  # noqa  (symbolic control flow)


# creation/scalar symbol ops the reference exposes at module level
# (hypot/histogram/slice come from the auto-registration already)
pow = _g["power"]  # noqa: A001  (ref symbol.py pow)


def split_v2(data, indices_or_sections, axis=0, squeeze_axis=False, name=None):
    """ref symbol.py split_v2 (sections/indices are static attrs)."""
    return Symbol(op=nd.split_v2, op_name="split_v2", inputs=[data],
                  kwargs=dict(indices_or_sections=indices_or_sections,
                              axis=axis, squeeze_axis=squeeze_axis),
                  name=name)


def eye(N, M=None, k=0, dtype="float32", **kw):
    from .symbol import Symbol
    return Symbol(op=lambda: nd.eye(N, M, k, dtype=dtype), op_name="eye",
                  inputs=[])


def full(shape, val, dtype="float32", **kw):
    from .symbol import Symbol
    return Symbol(op=lambda: nd.full(shape, val, dtype=dtype), op_name="full",
                  inputs=[])


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", name=None, **kw):
    from .symbol import Symbol
    return Symbol(op=lambda: nd.arange(start, stop, step, repeat=repeat,
                                       dtype=dtype),
                  op_name="arange", inputs=[], name=name)


def linspace(start, stop, num, endpoint=True, dtype="float32", **kw):
    from .symbol import Symbol
    import numpy as _onp
    return Symbol(op=lambda: nd.array(_onp.linspace(
        start, stop, num, endpoint=endpoint).astype(dtype)),
        op_name="linspace", inputs=[])


__all__ += ["pow", "split_v2", "eye", "full", "arange", "linspace"]


# -------------------------------------------------------- sub-namespaces
# (ref mx.sym.linalg / mx.sym.random / mx.sym.sparse generated namespaces)
class _SymNS:
    def __init__(self, name, table):
        self.__name__ = "symbol." + name
        for k, v in table.items():
            setattr(self, k, v)


def _sym_linalg_ns():
    from ..ndarray import linalg as _ndl
    table = {}
    for k in dir(_ndl):
        fn = getattr(_ndl, k)
        if k.startswith("_") or not callable(fn):
            continue
        table[k] = _symbolize(fn, "linalg_" + k)
        _OP_TABLE["linalg_" + k] = fn
    return _SymNS("linalg", table)


def _sym_random_ns():
    from ..ndarray import random as _ndr

    def make_creation(fn, opname):
        def sym_fn(*args, name=None, **kwargs):
            # creation-style: no Symbol inputs; args fold into the thunk
            return Symbol(op=lambda: fn(*args, **kwargs), op_name=opname,
                          inputs=[], name=name)
        sym_fn.__name__ = opname
        return sym_fn

    table = {}
    for k in ["uniform", "normal", "randn", "randint", "exponential",
              "gamma", "poisson", "negative_binomial",
              "generalized_negative_binomial", "bernoulli"]:
        if hasattr(_ndr, k):
            table[k] = make_creation(getattr(_ndr, k), "random_" + k)
    for k in ["multinomial", "shuffle"]:  # array-input ops
        if hasattr(_ndr, k):
            table[k] = _symbolize(getattr(_ndr, k), "random_" + k)
            _OP_TABLE["random_" + k] = getattr(_ndr, k)
    return _SymNS("random", table)


def _sym_sparse_ns():
    """mx.sym.sparse facade: sparse STORAGE is eager-only here (README
    §Sparse — data-dependent nnz can't live under jit), so the symbolic
    namespace maps the dense-compatible ops; storage-changing ops raise
    with the documented decision."""
    table = {"dot": _g["dot"], "add": _g["add"], "subtract": _g["subtract"],
             "multiply": _g["multiply"], "divide": _g["divide"]}

    def cast_storage(*a, **k):
        raise NotImplementedError(
            "symbolic cast_storage: sparse storage conversion is eager-only "
            "(data-dependent nnz; see README 'Sparse & async')")
    table["cast_storage"] = cast_storage
    return _SymNS("sparse", table)


linalg = _sym_linalg_ns()
random = _sym_random_ns()
sparse = _sym_sparse_ns()
__all__ += ["linalg", "random", "sparse", "BatchNorm_v1", "CuDNNBatchNorm",
            "Convolution_v1", "Pooling_v1"]
__all__ = list(dict.fromkeys(__all__))  # auto-registered names deduped
