"""Symbol — lazy graph API (ref python/mxnet/symbol/symbol.py:53).

TPU-native design: a Symbol is a lightweight expression DAG over the SAME pure
JAX op implementations the eager nd namespace uses (no separate kernel
registry). ``simple_bind`` traces the DAG once and jit-compiles it — NNVM
graph passes (fusion, memory planning) are delegated to XLA (SURVEY §7 table:
GraphExecutor+CachedOp collapse into compile-and-cache).
"""
from __future__ import annotations

import json

import numpy as onp

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json", "zeros",
           "ones"]


class Symbol:
    def __init__(self, op=None, op_name="", inputs=None, kwargs=None, name=None,
                 num_outputs=1, output_index=None):
        self._op = op                     # callable on NDArrays (nd namespace fn)
        self._op_name = op_name
        self._inputs = inputs or []       # list[Symbol]
        self._kwargs = kwargs or {}
        self._attr = {}
        self.name = name or _auto_name(op_name or "sym")
        self._num_outputs = num_outputs
        self._output_index = output_index  # not None → view of multi-output node

    # ---------------------------------------------------------------- graph
    @property
    def is_var(self):
        return self._op is None and not self._inputs

    def list_inputs(self):
        return self.list_arguments()

    def list_arguments(self):
        """Free variables in DFS order (ref symbol.py list_arguments)."""
        seen, order = set(), []

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            if s.is_var and s.name not in [o for o in order]:
                order.append(s.name)

        visit(self)
        return order

    def list_outputs(self):
        if self._num_outputs == 1 or self._output_index is not None:
            return [self.name + "_output"]
        return ["%s_output%d" % (self.name, i) for i in range(self._num_outputs)]

    def list_auxiliary_states(self):
        return []

    def get_internals(self):
        """All nodes as a Group (ref symbol.py get_internals)."""
        seen, order = set(), []

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            order.append(s)

        visit(self)
        return Group(order)

    def attr(self, key):
        return self._attr.get(key)

    def _set_attr(self, **kwargs):
        self._attr.update(kwargs)

    def attr_dict(self):
        """{node_name: {attr: str}} over the whole DAG (ref symbol.py attr_dict);
        consumed by Optimizer.set_lr_mult/set_wd_mult via __lr_mult__/__wd_mult__."""
        out = {}
        for s in self.get_internals():
            if s._attr:
                out.setdefault(s.name, {}).update(
                    {k: str(v) for k, v in s._attr.items()})
        return out

    def __getitem__(self, index):
        if isinstance(index, int):
            if self._num_outputs == 1:
                assert index == 0
                return self
            # memoized views sharing _base so eval_imperative caches the
            # producing op ONCE across all consumed outputs
            views = self.__dict__.setdefault("_views", {})
            if index not in views:
                v = Symbol(op=self._op, op_name=self._op_name, inputs=self._inputs,
                           kwargs=self._kwargs, name=self.name,
                           num_outputs=self._num_outputs, output_index=index)
                v._base = self._base if self._output_index is not None else self
                views[index] = v
            return views[index]
        raise TypeError("symbol index must be int")

    def __iter__(self):
        return iter([self[i] for i in range(self._num_outputs)])

    # ---------------------------------------------------------------- eval
    def eval_imperative(self, bindings, _cache=None):
        """Evaluate the DAG with NDArray bindings {name: NDArray}."""
        cache = _cache if _cache is not None else {}

        def ev(s):
            base = getattr(s, "_base", None) or s
            key = (id(base), s._output_index)
            base_key = (id(base), None)
            if key in cache:
                return cache[key]
            if s.is_var:
                if s.name not in bindings:
                    raise ValueError("unbound variable %r" % s.name)
                out = bindings[s.name]
            else:
                if base_key in cache:
                    full = cache[base_key]
                else:
                    args = [ev(i) for i in s._inputs]
                    full = s._op(*args, **s._kwargs)
                    cache[base_key] = full
                out = full[s._output_index] if s._output_index is not None else full
            cache[key] = out
            return out

        return ev(self)

    def eval(self, ctx=None, **kwargs):
        """ref symbol.py eval — returns list of NDArrays."""
        out = self.eval_imperative(kwargs)
        return out if isinstance(out, (list, tuple)) else [out]

    # ---------------------------------------------------------------- shapes
    def infer_shape(self, **kwargs):
        """ref symbol.py infer_shape — via jax.eval_shape on the traced DAG."""
        import jax

        names = self.list_arguments()
        unknown = [n for n in names if n not in kwargs]

        def fn(binding_datas):
            b = {k: NDArray(v) for k, v in binding_datas.items()}
            out = self.eval_imperative(b)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o._data for o in outs]

        if unknown:
            return None, None, None
        shapes = {k: jax.ShapeDtypeStruct(tuple(v), onp.float32)
                  for k, v in kwargs.items()}
        out_shapes = jax.eval_shape(fn, shapes)
        arg_shapes = [tuple(kwargs[n]) for n in names]
        return arg_shapes, [tuple(o.shape) for o in out_shapes], []

    def infer_type(self, **kwargs):
        names = self.list_arguments()
        return [onp.float32] * len(names), [onp.float32], []

    # ---------------------------------------------------------------- bind
    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        """Allocate args + compile (ref symbol.py:1507 → c_api_executor.cc:860)."""
        from ..executor import Executor

        args = {}
        by_name = {}
        seen = set()

        def visit(s):
            if id(s) in seen:
                return
            seen.add(id(s))
            for i in s._inputs:
                visit(i)
            if s.is_var:
                by_name[s.name] = s

        visit(self)
        for name in self.list_arguments():
            v = by_name.get(name)
            if name in shapes:
                args[name] = nd.zeros(shapes[name], ctx=ctx)
            elif v is not None and getattr(v, "_deferred_shape_fn", None):
                continue  # materialised by the Executor from data shapes
            else:
                raise ValueError("simple_bind needs shape for %r" % name)
        return Executor(self, ctx, args, grad_req=grad_req)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        """ref symbol.py bind."""
        from ..executor import Executor

        names = self.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(names, args_grad))
        return Executor(self, ctx, args, args_grad=args_grad, grad_req=grad_req)

    # ---------------------------------------------------------------- misc ops
    def _binop(self, other, fn, op_name, reverse=False):
        if isinstance(other, Symbol):
            ins = [other, self] if reverse else [self, other]
            return Symbol(op=fn, op_name=op_name, inputs=ins)
        # scalar operand: kept in kwargs so tojson/load_json round-trips
        # (ref _plus_scalar etc. op family)
        return Symbol(op=_scalar_binop_fn(fn), op_name=op_name + "_scalar",
                      inputs=[self],
                      kwargs={"scalar": other, "reverse": bool(reverse)})

    def __add__(self, o): return self._binop(o, nd.add, "_plus")
    def __radd__(self, o): return self._binop(o, nd.add, "_plus", True)
    def __sub__(self, o): return self._binop(o, nd.subtract, "_minus")
    def __rsub__(self, o): return self._binop(o, nd.subtract, "_minus", True)
    def __mul__(self, o): return self._binop(o, nd.multiply, "_mul")
    def __rmul__(self, o): return self._binop(o, nd.multiply, "_mul", True)
    def __truediv__(self, o): return self._binop(o, nd.divide, "_div")
    def __rtruediv__(self, o): return self._binop(o, nd.divide, "_div", True)
    def __pow__(self, o): return self._binop(o, nd.power, "_pow")
    # comparisons (ref symbol.py __gt__/__ge__/__lt__/__le__ → broadcast_*);
    # __eq__/__hash__ stay identity-based so symbols remain dict keys
    def __gt__(self, o): return self._binop(o, nd.greater, "_greater")
    def __ge__(self, o): return self._binop(o, nd.greater_equal, "_greater_equal")
    def __lt__(self, o): return self._binop(o, nd.lesser, "_lesser")
    def __le__(self, o): return self._binop(o, nd.lesser_equal, "_lesser_equal")
    def __mod__(self, o): return self._binop(o, nd.modulo, "_mod")
    def __neg__(self):
        return Symbol(op=lambda a: -a, op_name="negative", inputs=[self])

    def __repr__(self):
        return "<Symbol %s>" % self.name

    # ---------------------------------------------------------------- io
    def optimize_for(self, backend, args=None, ctx=None, **kwargs):
        """Partition this graph for a registered subgraph backend
        (ref python symbol.optimize_for / subgraph_property.h:252)."""
        from ..subgraph import partition
        return partition(self, backend)

    def get_backend_symbol(self, backend):
        """Legacy alias of optimize_for (ref symbol.py get_backend_symbol)."""
        return self.optimize_for(backend)

    def tojson(self):
        """Graph JSON (structural; op impls are named, not serialized)."""
        nodes, index = [], {}

        def visit(s):
            if id(s) in index:
                return index[id(s)]
            inputs = [visit(i) for i in s._inputs]
            idx = len(nodes)
            nodes.append({
                "op": "null" if s.is_var else s._op_name,
                "name": s.name,
                "inputs": [[i, 0, 0] for i in inputs],
                "attrs": {k: str(v) for k, v in s._kwargs.items()},
            })
            index[id(s)] = idx
            return idx

        visit(self)
        return json.dumps({"nodes": nodes, "format": "incubator_mxnet_tpu.symbol",
                           "heads": [[len(nodes) - 1, 0, 0]]}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())


class Group(Symbol):
    """Multiple outputs grouped (ref symbol.py Group)."""

    def __init__(self, symbols):
        super().__init__(op_name="_group", name=_auto_name("group"))
        self._symbols = list(symbols)
        # children double as graph inputs so DAG walks (get_internals,
        # attr_dict) reach them; Group overrides eval_imperative so the
        # no-op _op is never applied
        self._inputs = list(symbols)
        self._num_outputs = len(self._symbols)

    def eval_imperative(self, bindings, _cache=None):
        cache = _cache if _cache is not None else {}
        return [s.eval_imperative(bindings, cache) for s in self._symbols]

    def list_arguments(self):
        seen, order = [], []
        for s in self._symbols:
            for n in s.list_arguments():
                if n not in order:
                    order.append(n)
        return order

    def list_outputs(self):
        return sum((s.list_outputs() for s in self._symbols), [])

    def __getitem__(self, i):
        return self._symbols[i]


_NAME_COUNT = {}


def _auto_name(hint):
    c = _NAME_COUNT.get(hint, 0)
    _NAME_COUNT[hint] = c + 1
    return "%s%d" % (hint, c)


def _const(v, like):
    return v


_SCALAR_FNS = {}


def _scalar_binop_fn(fn):
    """Kwargs-driven scalar-binop impl, one cached fn per base op so
    load_json can resolve '<name>_scalar' nodes (see symbol/__init__)."""
    if fn not in _SCALAR_FNS:
        def op(a, scalar=0.0, reverse=False, _fn=fn):
            return _fn(_const(scalar, a), a) if reverse else _fn(a, scalar)
        _SCALAR_FNS[fn] = op
    return _SCALAR_FNS[fn]


def var(name, shape=None, dtype=None, lr_mult=None, wd_mult=None, init=None,
        **kwargs):
    """Free variable (ref symbol.py var): lr_mult/wd_mult/attr kwargs become
    __lr_mult__/__wd_mult__/... node attributes consumed via attr_dict()."""
    s = Symbol(name=name)
    s._shape = shape
    s._dtype = dtype
    if lr_mult is not None:
        kwargs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        kwargs["__wd_mult__"] = wd_mult
    if init is not None:
        kwargs["__init__"] = init
    if kwargs:
        s._set_attr(**kwargs)
    return s


Variable = var


def zeros(shape, dtype="float32", **kw):
    return Symbol(op=lambda: nd.zeros(shape, dtype=dtype), op_name="zeros",
                  inputs=[])


def ones(shape, dtype="float32", **kw):
    return Symbol(op=lambda: nd.ones(shape, dtype=dtype), op_name="ones",
                  inputs=[])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Rebuild a Symbol DAG from graph JSON (op impls resolved from nd)."""
    from . import _op_lookup, _deferred_rules

    graph = json.loads(json_str)
    nodes = graph["nodes"]
    built = []
    for node in nodes:
        if node["op"] == "null":
            built.append(var(node["name"]))
        else:
            fn = _op_lookup(node["op"])
            inputs = [built[i[0]] for i in node["inputs"]]
            kwargs = {k: _parse_attr(v) for k, v in node.get("attrs", {}).items()}
            if "__arg_spec__" in kwargs:
                # list-of-arrays op: restore the flat→structured adapter
                from . import _flat_adapter
                fn = _flat_adapter(fn, tuple(kwargs["__arg_spec__"]))
            # restore deferred-shape rules on auto-created parameter vars
            rules = _deferred_rules(node["op"], kwargs)
            for idx, shape_fn in (rules or {}).items():
                if idx < len(inputs) and inputs[idx].is_var:
                    v = inputs[idx]
                    if not hasattr(v, "_deferred_shape_fn"):
                        v._deferred_shape_fn = shape_fn
                        v._is_param = True
                        if node["op"] == "BatchNorm" and idx >= 3:
                            v._is_aux = True
            s = Symbol(op=fn, op_name=node["op"],
                       inputs=inputs, kwargs=kwargs, name=node["name"])
            built.append(s)
    return built[graph["heads"][0][0]]


def _parse_attr(v):
    import ast
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def _bind_kwargs(fn, kwargs):
    def wrapped(*args, **kw):
        merged = dict(kwargs)
        merged.update(kw)
        return fn(*args, **merged)
    return wrapped
