"""Shared small utilities (ref: python/mxnet/base.py, python/mxnet/registry.py)."""
from __future__ import annotations

import numpy as onp

__all__ = ["MXNetError", "string_types", "numeric_types", "registry",
           "Registry", "public_op_names"]


class MXNetError(RuntimeError):
    """Framework error type (ref: python/mxnet/base.py MXNetError)."""


string_types = (str,)
numeric_types = (float, int, onp.generic)


class Registry:
    """Name→class registry with alias support (ref: python/mxnet/registry.py)."""

    def __init__(self, name):
        self.name = name
        self._registry = {}

    def register(self, klass, name=None):
        nm = (name or klass.__name__).lower()
        self._registry[nm] = klass
        return klass

    def alias(self, *aliases):
        def reg(klass):
            self.register(klass)
            for a in aliases:
                self.register(klass, a)
            return klass

        return reg

    def get(self, name):
        if isinstance(name, str):
            key = name.lower()
            if key not in self._registry:
                raise ValueError(
                    "%s %r not registered; known: %s" % (self.name, name, sorted(self._registry))
                )
            return self._registry[key]
        return name

    def create(self, name, *args, **kwargs):
        if not isinstance(name, str):
            return name
        return self.get(name)(*args, **kwargs)

    def __contains__(self, name):
        return isinstance(name, str) and name.lower() in self._registry

    def keys(self):
        return self._registry.keys()


_registries = {}


def registry(name):
    if name not in _registries:
        _registries[name] = Registry(name)
    return _registries[name]


def public_op_names(namespace, exclude=()):
    """Public operator-like callables of a namespace: everything that is
    not underscored, a module, a class, or in ``exclude``. The ONE
    eligibility rule shared by the nd→sym auto-registration
    (symbol/__init__.py), the registry sweep coverage contract
    (test_utils.sweep_coverage), and the parity tests — so the three can
    never disagree about what counts as an op."""
    import inspect
    import types
    out = []
    for n in sorted(dir(namespace)):
        if n.startswith("_") or n in exclude:
            continue
        o = getattr(namespace, n)
        if isinstance(o, types.ModuleType) or inspect.isclass(o) or \
                not callable(o):
            continue
        out.append(n)
    return out
