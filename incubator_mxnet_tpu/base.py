"""Shared small utilities (ref: python/mxnet/base.py, python/mxnet/registry.py)."""
from __future__ import annotations

import numpy as onp

__all__ = ["MXNetError", "string_types", "numeric_types", "registry",
           "Registry", "public_op_names", "enable_x64"]


class MXNetError(RuntimeError):
    """Framework error type (ref: python/mxnet/base.py MXNetError)."""


def distributed_is_initialized():
    """``jax.distributed.is_initialized()`` resolved against the
    installed jax: older releases never exposed the query — there, the
    coordination client on ``jax._src.distributed.global_state`` is the
    ground truth (None until ``initialize()`` ran). Callers use this so
    double-initialization is avoided on every jax, not just current
    ones."""
    import jax
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src import distributed as _distributed
        return _distributed.global_state.client is not None
    except Exception:
        return False


def enable_x64(enabled=True):
    """``jax.enable_x64`` resolved against the installed jax.

    Newer jax exposes the scoped 64-bit-dtype switch at top level; a
    long range of releases only as ``jax.experimental.enable_x64``.
    Every int64/float64 code path (ndarray dtype handling, kvstore
    wide-dtype batching) resolves it HERE so the installed jax decides
    once — not as an AttributeError inside an op."""
    import jax
    fn = getattr(jax, "enable_x64", None)
    if fn is None:
        from jax.experimental import enable_x64 as fn
    return fn(enabled)


string_types = (str,)
numeric_types = (float, int, onp.generic)


class Registry:
    """Name→class registry with alias support (ref: python/mxnet/registry.py)."""

    def __init__(self, name):
        self.name = name
        self._registry = {}

    def register(self, klass, name=None):
        nm = (name or klass.__name__).lower()
        self._registry[nm] = klass
        return klass

    def alias(self, *aliases):
        def reg(klass):
            self.register(klass)
            for a in aliases:
                self.register(klass, a)
            return klass

        return reg

    def get(self, name):
        if isinstance(name, str):
            key = name.lower()
            if key not in self._registry:
                raise ValueError(
                    "%s %r not registered; known: %s" % (self.name, name, sorted(self._registry))
                )
            return self._registry[key]
        return name

    def create(self, name, *args, **kwargs):
        if not isinstance(name, str):
            return name
        return self.get(name)(*args, **kwargs)

    def __contains__(self, name):
        return isinstance(name, str) and name.lower() in self._registry

    def keys(self):
        return self._registry.keys()


_registries = {}


def registry(name):
    if name not in _registries:
        _registries[name] = Registry(name)
    return _registries[name]


def public_op_names(namespace, exclude=()):
    """Public operator-like callables of a namespace: everything that is
    not underscored, a module, a class, or in ``exclude``. The ONE
    eligibility rule shared by the nd→sym auto-registration
    (symbol/__init__.py), the registry sweep coverage contract
    (test_utils.sweep_coverage), and the parity tests — so the three can
    never disagree about what counts as an op."""
    import inspect
    import types
    out = []
    for n in sorted(dir(namespace)):
        if n.startswith("_") or n in exclude:
            continue
        o = getattr(namespace, n)
        if isinstance(o, types.ModuleType) or inspect.isclass(o) or \
                not callable(o):
            continue
        out.append(n)
    return out
