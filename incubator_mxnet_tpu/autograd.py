"""Imperative autograd — tape over eagerly executed JAX ops.

Reference parity: python/mxnet/autograd.py (record/pause scopes :120, backward
:244, grad :271, custom Function :388) and the C++ tape in
src/imperative/imperative.cc (RecordOp :193, Backward :280).

TPU-native design: instead of an NNVM graph + engine replay, every recorded op
stores its *pure JAX function* and inputs. ``backward`` walks the tape in
reverse and calls ``jax.vjp`` per entry — XLA compiles each op's VJP; no
hand-written gradient kernels exist anywhere in this framework. The fast path
(hybridize / jitted train step) bypasses the tape entirely and differentiates
the whole step with ``jax.grad``.
"""
from __future__ import annotations

import threading

import jax
import numpy as onp

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "mark_variables",
    "backward",
    "grad",
    "set_recording",
    "set_training",
    "Function",
]


class _TapeEntry:
    __slots__ = ("fn", "inputs", "in_data", "outputs", "n_outputs", "custom_backward")

    def __init__(self, fn, inputs, outputs):
        self.fn = fn            # pure function: (*jax arrays) -> jax array or tuple
        self.inputs = inputs    # list[NDArray]
        # snapshot input buffers at record time so later in-place writes on the
        # NDArray (x += y rebinds ._data) don't corrupt the replayed VJP
        self.in_data = [x._data for x in inputs]
        self.outputs = outputs  # list[NDArray]
        self.n_outputs = len(outputs)


class _AutogradState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape = []


_STATE = _AutogradState()


def is_recording():
    return _STATE.recording


def is_training():
    return _STATE.training


def set_recording(is_record):
    prev = _STATE.recording
    _STATE.recording = bool(is_record)
    return prev


def set_training(train_mode_):
    prev = _STATE.training
    _STATE.training = bool(train_mode_)
    return prev


class _RecordingStateScope:
    def __init__(self, is_record, train_mode_):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode_
        self._prev = None

    def __enter__(self):
        self._prev = (_STATE.recording, _STATE.training)
        if self._enter_is_record is not None:
            _STATE.recording = self._enter_is_record
        if self._enter_train_mode is not None:
            _STATE.training = self._enter_train_mode
        return self

    def __exit__(self, *args):
        _STATE.recording, _STATE.training = self._prev


def record(train_mode=True):
    """Scope in which executed ops are taped (ref: autograd.py:120)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def _record_op(fn, inputs, outputs):
    """Called from ndarray._apply for every eager op while recording."""
    tracked = [x for x in inputs if getattr(x, "_in_graph", False)]
    if not tracked:
        return
    for o in outputs:
        o._in_graph = True
    _STATE.tape.append(_TapeEntry(fn, list(inputs), list(outputs)))


def mark_variables(variables, gradients, grad_reqs="write"):
    """attach_grad: mark arrays as differentiation roots (ref: imperative.cc:123)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, gradient, req in zip(variables, gradients, grad_reqs):
        var._in_graph = True
        var._grad_req = req
        var.grad_buf = gradient


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads w.r.t. marked variables (ref: autograd.py:244).

    Walks the tape in reverse; per-entry cotangents via jax.vjp.
    """
    from .ndarray.ndarray import NDArray, array as _nd_array

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # cotangent accumulator keyed by array identity
    cotangent = {}
    for h, hg in zip(heads, head_grads):
        g = jax.numpy.ones_like(h._data) if hg is None else hg._data
        key = id(h)
        cotangent[key] = cotangent.get(key, 0) + g

    tape = _STATE.tape
    for entry in reversed(tape):
        out_cts = [cotangent.get(id(o)) for o in entry.outputs]
        if all(ct is None for ct in out_cts):
            continue
        if hasattr(entry, "custom_backward"):
            cts_in = entry.custom_backward(out_cts)
            for x, ct in zip(entry.inputs, cts_in):
                if ct is None or not getattr(x, "_in_graph", False):
                    continue
                key = id(x)
                cotangent[key] = cotangent.get(key, 0) + ct if key in cotangent else ct
            continue
        in_data = entry.in_data
        primals_out, vjp_fn = jax.vjp(entry.fn, *in_data)
        if isinstance(primals_out, (tuple, list)):
            seed = [ct if ct is not None else jax.numpy.zeros_like(p)
                    for ct, p in zip(out_cts, primals_out)]
            seed = tuple(seed) if isinstance(primals_out, tuple) else seed
        else:
            seed = (out_cts[0] if out_cts[0] is not None
                    else jax.numpy.zeros_like(primals_out))
        cts_in = vjp_fn(seed)
        for x, ct in zip(entry.inputs, cts_in):
            if ct is None or not getattr(x, "_in_graph", False):
                continue
            key = id(x)
            cotangent[key] = cotangent.get(key, 0) + ct if key in cotangent else ct

    # write into .grad of marked variables
    seen = set()
    for entry in tape:
        for x in entry.inputs:
            if id(x) in seen:
                continue
            seen.add(id(x))
            _write_grad(x, cotangent)
    for h in heads:
        if id(h) not in seen:
            _write_grad(h, cotangent)

    if not retain_graph:
        _STATE.tape = []


def _write_grad(x, cotangent):
    buf = getattr(x, "grad_buf", None)
    if buf is None:
        return
    ct = cotangent.get(id(x))
    if ct is None:
        return
    req = getattr(x, "_grad_req", "write")
    if req == "null":
        return
    if req == "add":
        buf._data = buf._data + ct
    else:
        buf._data = jax.numpy.asarray(ct, dtype=buf._data.dtype)


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Functional gradient (ref: autograd.py:271): returns grads, leaves .grad alone."""
    from .ndarray.ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    else:
        single = False
    # stash existing grad buffers, attach temps
    saved = [(getattr(v, "grad_buf", None), getattr(v, "_grad_req", None)) for v in variables]
    temps = []
    for v in variables:
        t = NDArray(jax.numpy.zeros_like(v._data), ctx=v.ctx)
        v._in_graph = True
        v._grad_req = "write"
        v.grad_buf = t
        temps.append(t)
    backward(heads, head_grads, retain_graph=bool(retain_graph) or create_graph,
             train_mode=train_mode)
    for v, (buf, req) in zip(variables, saved):
        v.grad_buf = buf
        if req is not None:
            v._grad_req = req
    return temps[0] if single else temps


class Function:
    """Custom differentiable function (ref: autograd.py:388-513).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` in terms of NDArray ops.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        single = isinstance(outputs, NDArray)
        outs = [outputs] if single else list(outputs)
        if is_recording() and any(getattr(x, "_in_graph", False) for x in inputs):
            func = self
            entry = _TapeEntry(None, list(inputs), outs)

            # monkey-patch: custom entries carry their own backward
            def run_backward(out_cts):
                cts = func.backward(
                    *[NDArray(ct) if ct is not None else None for ct in out_cts]
                )
                if isinstance(cts, NDArray):
                    cts = (cts,)
                return [c._data if c is not None else None for c in cts]

            entry.custom_backward = run_backward
            for o in outs:
                o._in_graph = True
            _STATE.tape.append(entry)
        return outputs
