"""Fused training step — whole-step compilation with donated buffers.

This is the TPU-native analog of the reference's hot path: GraphExecutor op
bulking (src/executor/graph_executor.cc:1368 BulkOpSegs + :1449 bulk segments)
plus optimizer-as-op (src/operator/optimizer_op.cc multi_sgd): ONE XLA program
computes forward, backward, and every parameter/optimizer-state update, with
input buffers donated so updates are in-place on device (kWriteInplace analog).

Usage::

    step = TrainStep(net, loss_fn, trainer)
    loss = step(x, y)          # one compiled step; params/state updated

Data-parallel over a mesh: see parallel.DataParallelTrainStep, which shards
the batch axis of this same program.
"""
from __future__ import annotations

import logging
import threading as _threading
import time as _time

import jax
import jax.numpy as jnp

from . import aot
from . import autograd
from . import config
from . import telemetry
from .telemetry import devstats, flightrec, numwatch, spans, watchdog
from .gluon import _functional
from .ndarray import NDArray
from .ndarray import random as _rnd

_LOG = logging.getLogger(__name__)


def _donate(argnums):
    """Buffer donation unless MXTPU_NO_DONATE (debugging) is set."""
    return () if config.get_env("MXTPU_NO_DONATE") else argnums


# Per-net trace/dispatch synchronization. Tracing a step/eval program
# swaps TRACERS into the live Parameter NDArrays' ``_data`` and restores
# them after (gluon/_functional pure_fn, TrainStep._build inner) — so for
# the duration of a trace, the net's params hold tracers, and any other
# thread reading ``a._data`` (a concurrent trace of another bucket, or a
# HIT dispatch capturing its argument list) would hand a tracer to a
# compiled executable. The registry's prewarm thread made this reachable:
# after the early cutover the batcher worker dispatches the same net the
# warm thread is still tracing bigger buckets of. Discipline: every TRACE
# window holds the net's lock exclusively; every dispatch captures its
# ``_data`` snapshot under the same lock (sub-µs when uncontended) and
# executes outside it. The lock lives on the net object itself so every
# component tracing one net (EvalStep, TrainStep, multiple instances)
# shares it; it is keyed per net, so one model's compile never stalls
# another model's traffic.
_TRACE_LOCK_REGISTRY = _threading.Lock()


def _net_trace_lock(net):
    lock = getattr(net, "_mxtpu_trace_lock", None)
    if lock is None:
        with _TRACE_LOCK_REGISTRY:      # double-checked: one lock per net
            lock = getattr(net, "_mxtpu_trace_lock", None)
            if lock is None:
                lock = _threading.RLock()
                net._mxtpu_trace_lock = lock
    return lock

__all__ = ["TrainStep", "EvalStep"]

# Compile observability: each shared-cache (aot.CACHE) miss that cannot be
# satisfied by a persisted artifact is one model trace + XLA compile.
# Single-device train programs AOT-compile inside the build (jit().lower()
# .compile() with the step's arg specs — which also hands devstats the
# compiled program's cost/memory analysis); mesh-train wrappers still
# compile lazily on the first dispatch. Either way the miss's whole
# first step — trace + compile + run — is what gets attributed to compile
# time. Watching compiles_total climb under bucketed variable-shape
# traffic is how an undersized MXTPU_AOT_CACHE_SIZE shows itself (so is
# mxtpu_aot_evictions_total, its direct cause).
_COMPILES = telemetry.counter(
    "mxtpu_jit_compiles_total",
    "Shape-keyed executable-cache misses (one XLA compile each).",
    ("kind",))
_COMPILE_SECONDS = telemetry.counter(
    "mxtpu_jit_compile_seconds_total",
    "Wall seconds spent in cache-miss first steps (trace+compile+run).",
    ("kind",))
_STEP_SECONDS = telemetry.histogram(
    "mxtpu_train_step_seconds",
    "Wall time per TrainStep call (cache-hit steady state included).",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0, 10.0, 30.0, 60.0))
_STEPS = telemetry.counter(
    "mxtpu_train_steps_total", "Completed TrainStep calls.")
_EXAMPLES = telemetry.counter(
    "mxtpu_train_examples_total",
    "Examples consumed by TrainStep (batch-size sum); rate() of this is "
    "examples/sec.")


def _record_compile_span(name, dur_s):
    """Retroactive span for a just-finished compile window (jax.jit
    compiles lazily inside the first call, so the window is only
    measurable after the fact), parented onto the ambient step span."""
    try:
        from . import profiler
        spans.record_span(name, profiler.now_us() - dur_s * 1e6,
                          dur_s * 1e6, parent=spans.current_span())
    except Exception:   # tracing must never fail the step
        pass


def _tree_to_data(state):
    """Nested optimizer state (NDArrays in tuples) -> pytree of jax arrays."""
    if state is None:
        return None
    if isinstance(state, NDArray):
        return state._data
    if isinstance(state, (tuple, list)):
        return tuple(_tree_to_data(s) for s in state)
    return state


def _tree_wrap(data):
    """pytree of jax arrays -> nested NDArrays (fresh wrappers)."""
    if data is None:
        return None
    if isinstance(data, (tuple, list)):
        return tuple(_tree_wrap(d) for d in data)
    return NDArray(data)


class TrainStep:
    """Compile net forward + loss + backward + optimizer update into one program."""

    def __init__(self, net, loss_fn, trainer, batch_axis=0, grad_postprocess=None,
                 mesh=None, data_axis="dp", remat=None, zero=False,
                 model_id=None):
        self.net = net
        self.loss_fn = loss_fn
        self.trainer = trainer
        self._grad_postprocess = grad_postprocess
        # shared-executable-cache identity: train entries carry per-call
        # python state (param/aux NDArray lists bound to THIS net), so the
        # default id is instance-scoped — entries are released in __del__
        self._model_id = model_id
        self._cache_keys = set()
        self._trace_lock = _net_trace_lock(net)
        self._step_count = 0
        self.mesh = mesh
        self.data_axis = data_axis
        self.batch_axis = batch_axis
        # remat: rematerialize the forward during backward (jax.checkpoint)
        # — trades ~1 extra forward of FLOPs for O(layer) activation memory,
        # the long-sequence HBM lever (SURVEY §7 guidance)
        from .config import get_env
        self.remat = get_env("MXTPU_REMAT") if remat is None else remat
        # zero: ZeRO-1 / automatic cross-replica sharding of the weight
        # update (arXiv:2004.13336, the GSPMD-annotation form): optimizer
        # states (incl. fp32 masters) are SHARDED over the dp axis on dim 0,
        # so state memory and update FLOPs divide by |dp|; the sharding
        # mismatch makes XLA lower the grad all-reduce to reduce-scatter and
        # all-gather the updated weights — no hand-written collectives.
        # Params themselves stay replicated (ZeRO-1, not 2/3).
        self.zero = zero
        # device truth of the most recently dispatched program (aot entry
        # stats: flops / bytes_accessed / peak_bytes / output_bytes), or
        # None pre-dispatch / on the lazy mesh path — what bench.py's
        # cost-analysis-derived MFU reads
        self._last_stats = None
        # watchdog bookkeeping: counts once this instance starts stepping
        self._hb_registered = False

    # ------------------------------------------------------------------
    def _split_params(self):
        params = list(self.net.collect_params().values())
        trainable = [p for p in params if p.grad_req != "null"]
        frozen = [p for p in params if p.grad_req == "null"]
        return trainable, frozen

    def _build(self, meta, n_inputs):
        trainable, frozen = self._split_params()
        t_arrs = [p.data() for p in trainable]
        f_arrs = [p.data() for p in frozen]
        net, loss_fn = self.net, self.loss_fn
        optimizer = self.trainer._optimizer
        aux_box = []

        def inner(t_datas, f_datas, input_datas, key):
            saved_t = [a._data for a in t_arrs]
            saved_f = [a._data for a in f_arrs]
            for a, d in zip(t_arrs, t_datas):
                a._data = d
            for a, d in zip(f_arrs, f_datas):
                a._data = d
            try:
                with _functional.FunctionalScope(key) as st:
                    with autograd.pause(train_mode=True):
                        nd_inputs = [NDArray(d) for d in input_datas]
                        # bypass hybridize's own cache: trace the eager forward
                        out = net.forward(*nd_inputs[:n_inputs])
                        outs = out if isinstance(out, (list, tuple)) else (out,)
                        loss = loss_fn.forward(outs[0] if len(outs) == 1 else outs,
                                               *nd_inputs[n_inputs:])
                    # seed-of-ones semantics: grads of the SUM; Trainer's
                    # rescale_grad (1/batch) then normalises — matches eager
                    loss_scalar = loss._data.sum()
                    aux_pairs = list(st.aux_updates)
            finally:
                for a, s in zip(t_arrs, saved_t):
                    a._data = s
                for a, s in zip(f_arrs, saved_f):
                    a._data = s
            aux_box[:] = [a for a, _ in aux_pairs]
            return loss_scalar, (loss._data, [v for _, v in aux_pairs])

        fwd = jax.checkpoint(inner) if self.remat else inner

        # step_fn must NOT close over self: the compiled entry lives in
        # the process-wide aot.CACHE, and an entry pinning its TrainStep
        # would keep __del__ (which releases the entry) from ever running
        # — capture the needed config as plain locals instead
        grad_postprocess = self._grad_postprocess
        constrain_update = self._make_constrainer(trainable)

        def step_fn(t_datas, f_datas, opt_states, input_datas, key, lrs, wds, t,
                    rescale):
            (loss_scalar, (loss_full, aux_vals)), grads = jax.value_and_grad(
                fwd, argnums=0, has_aux=True)(t_datas, f_datas, input_datas, key)
            if grad_postprocess is not None:
                grads = grad_postprocess(grads)
            new_t, new_opt = [], []
            lowp = (jnp.bfloat16, jnp.float16)
            for i, (w, g, s) in enumerate(zip(t_datas, grads, opt_states)):
                g = g * rescale
                if optimizer.clip_gradient is not None:
                    g = jnp.clip(g, -optimizer.clip_gradient, optimizer.clip_gradient)
                gf = g.astype(jnp.float32)
                mp = optimizer.multi_precision and w.dtype in lowp
                if mp:
                    # fp32 master-weight flow (ref optimizer.py:320): state is
                    # (master, inner); update the master, cast down the copy
                    master, inner_state = s
                    state_nd = _tree_wrap(inner_state)
                    new_w, new_state_nd = optimizer.update_rule(
                        master, gf, state_nd, lrs[i], wds[i], t)
                    new_t.append(new_w.astype(w.dtype))
                    new_opt.append((new_w, _tree_to_data(new_state_nd)))
                else:
                    state_nd = _tree_wrap(s)
                    new_w, new_state_nd = optimizer.update_rule(
                        w.astype(jnp.float32), gf, state_nd, lrs[i], wds[i], t)
                    new_t.append(new_w.astype(w.dtype))
                    new_opt.append(_tree_to_data(new_state_nd))
            if constrain_update is not None:
                new_t, new_opt = constrain_update(new_t, new_opt)
            return loss_full, new_t, new_opt, aux_vals

        if self.mesh is not None:
            jitted = self._jit_sharded(step_fn, trainable, frozen)
        else:
            jitted = jax.jit(step_fn, donate_argnums=_donate((0, 2)))
        return jitted, trainable, frozen, t_arrs, f_arrs, aux_box

    def _build_entry(self, n_inputs, arg_specs=None):
        """aot.compile_cached build hook: (compiled callable, instance
        extras, no exported artifact — train programs stay in-memory).

        With ``arg_specs`` (the single-device path), the program is
        AOT-compiled HERE — ``jit().lower(specs).compile()`` under the
        net's trace lock, the same explicit pipeline EvalStep uses — so
        the XLA compile lands inside the train:build span instead of
        lazily inside the first dispatch, and the cache entry is an
        analyzable compiled program (devstats harvests its cost/memory
        analysis at insert). A failed lower/compile degrades to the
        classic lazy-jit behavior (debug-logged), never to a broken
        step."""
        jitted, trainable, frozen, t_arrs, f_arrs, aux_box = \
            self._build(None, n_inputs)
        if arg_specs is not None and self.mesh is None:
            try:
                # the trace swaps tracers into the live param NDArrays
                # (inner's _data swap) — hold the net's trace lock for
                # the whole window, exactly like the eval build
                with self._trace_lock:
                    jitted = jitted.lower(*arg_specs).compile()
            except Exception:
                _LOG.debug("train AOT lower/compile failed; program "
                           "compiles lazily on first dispatch",
                           exc_info=True)
        return jitted, (trainable, frozen, t_arrs, f_arrs, aux_box), None

    def _arg_specs(self, arrs, key):
        """jax.ShapeDtypeStruct tree matching one step_fn call — what
        _build_entry AOT-lowers with. None (→ lazy compile, no program
        stats) on the mesh path or when any piece is unavailable."""
        if self.mesh is not None:
            return None
        try:
            def sds(x):
                return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)

            trainer = self.trainer
            trainable, frozen = self._split_params()
            t_specs = [sds(p.data()._data) for p in trainable]
            f_specs = [sds(p.data()._data) for p in frozen]
            opt_specs = []
            for i, p in enumerate(trainable):
                idx = trainer._param2idx.get(p.name, i)
                opt_specs.append(jax.tree_util.tree_map(
                    sds, _tree_to_data(trainer._states[idx])))
            in_specs = [sds(a._data) for a in arrs]
            vec = jax.ShapeDtypeStruct((len(trainable),), jnp.float32)
            return (t_specs, f_specs, opt_specs, in_specs, sds(key),
                    vec, vec, jax.ShapeDtypeStruct((), jnp.int32),
                    jax.ShapeDtypeStruct((), jnp.float32))
        except Exception:
            _LOG.debug("train arg-spec construction failed; program "
                       "compiles lazily on first dispatch", exc_info=True)
            return None

    def _zero_leaf_sharding(self, p):
        """Per-leaf optimizer-state sharding rule under zero=True: shard
        dim 0 over the dp axis when divisible (masters/momenta share the
        param shape); scalars and indivisible leaves replicate; params a
        tensor/expert-parallel layer already sharded keep their spec."""
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(self.mesh, PartitionSpec())
        if not self.zero or self.mesh is None \
                or self.mesh.shape.get(self.data_axis, 1) <= 1 \
                or getattr(p, "sharding", None) is not None:
            base = self._param_sharding(p)
            return lambda leaf: base
        n = self.mesh.shape[self.data_axis]
        dp = self.data_axis

        def rule(leaf):
            shape = getattr(leaf, "shape", ())
            if len(shape) >= 1 and shape[0] and shape[0] % n == 0:
                return NamedSharding(
                    self.mesh,
                    PartitionSpec(dp, *([None] * (len(shape) - 1))))
            return repl

        return rule

    def _make_constrainer(self, trainable):
        """Build the update-sharding constrainer (zero mode): new states
        stay dp-sharded, new weights return to their (replicated/TP) param
        sharding — the mismatch is what GSPMD lowers to
        reduce-scatter + sharded update + all-gather. Returns None when
        inactive; the returned closure is SELF-FREE (sharding rules are
        resolved here, at build time) so the shared-cache entry never pins
        this instance."""
        if not self.zero or self.mesh is None:
            return None
        rules = [self._zero_leaf_sharding(p) for p in trainable]
        shards = [self._param_sharding(p) for p in trainable]

        def constrain(new_t, new_opt):
            out_t, out_opt = [], []
            for w, s, rule, shard in zip(new_t, new_opt, rules, shards):
                out_t.append(jax.lax.with_sharding_constraint(w, shard))
                out_opt.append(jax.tree_util.tree_map(
                    lambda leaf, _r=rule: jax.lax.with_sharding_constraint(
                        leaf, _r(leaf)), s))
            return out_t, out_opt

        return constrain

    def _param_sharding(self, p):
        """Per-parameter sharding: p.sharding (a PartitionSpec) if set by a
        tensor/expert-parallel layer, else fully replicated."""
        from jax.sharding import NamedSharding, PartitionSpec
        if getattr(p, "sharding", None) is not None:
            spec = p.sharding
            if isinstance(spec, NamedSharding):
                return spec
            return NamedSharding(self.mesh, spec)
        return NamedSharding(self.mesh, PartitionSpec())

    def _jit_sharded(self, step_fn, trainable, frozen):
        """SPMD data(+tensor)-parallel: inputs sharded on the batch axis over
        ``data_axis``; params/optimizer state follow their own shardings. XLA
        inserts the gradient all-reduce (psum over dp) automatically — this IS
        the kvstore dist_device_sync path on ICI (SURVEY §2.5 north star)."""
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(self.mesh, PartitionSpec())
        t_sh = [self._param_sharding(p) for p in trainable]
        f_sh = [self._param_sharding(p) for p in frozen]
        data_sh = NamedSharding(self.mesh, PartitionSpec(self.data_axis))
        jitted = jax.jit(step_fn, donate_argnums=_donate((0, 2)))

        state_rules = [self._zero_leaf_sharding(p) for p in trainable]

        def wrapper(t_datas, f_datas, opt_states, input_datas, *rest):
            # lay out operands on the mesh; no-op once steady-state shardings
            # are established (outputs inherit them), so the reshard cost is
            # first-step-only
            t_datas = [jax.device_put(d, s) for d, s in zip(t_datas, t_sh)]
            f_datas = [jax.device_put(d, s) for d, s in zip(f_datas, f_sh)]
            opt_states = [jax.tree_util.tree_map(
                lambda x, _r=r: jax.device_put(x, _r(x)), st)
                for st, r in zip(opt_states, state_rules)]
            input_datas = [jax.device_put(d, data_sh) for d in input_datas]
            rest = [jax.device_put(r, repl) for r in rest]
            return jitted(t_datas, f_datas, opt_states, input_datas, *rest)

        return wrapper

    # ------------------------------------------------------------------
    #: live instances that have stepped at least once — the shared
    #: "train_step" heartbeat channel is unregistered when the LAST one is
    #: dropped, so a finished training loop (step object released) does
    #: not read as a stall forever after
    _hb_live = 0

    def __call__(self, *inputs, batch_size=None, n_net_inputs=1):
        """inputs = (*net_inputs, *loss_extra_args); returns per-sample loss."""
        if not self._hb_registered:
            # register on FIRST step, not construction: a step built long
            # before training starts must not page while idle
            self._hb_registered = True
            TrainStep._hb_live += 1
        watchdog.heartbeat("train_step")
        with spans.span("train:step"):
            return self._call_traced(inputs, batch_size, n_net_inputs)

    def __del__(self):
        try:
            if self._hb_registered:
                TrainStep._hb_live -= 1
                if TrainStep._hb_live <= 0:
                    watchdog.unregister("train_step")
            # train entries are instance-scoped (their extras pin THIS
            # net's param arrays): release them instead of waiting for LRU
            for key in self._cache_keys:
                aot.CACHE.discard(key)
        except Exception:
            pass          # interpreter-teardown __del__ must never raise

    def _call_traced(self, inputs, batch_size, n_net_inputs):
        # host-transfer child span: raw host arrays become device arrays
        # here (a no-op wrap for inputs already on device)
        with spans.span("train:host_transfer"):
            arrs = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a))
                    for a in inputs]
        if batch_size is None:
            batch_size = arrs[0].shape[0]
        trainer = self.trainer
        # trigger any deferred parameter init with one eager forward
        if any(p._data is None for p in self.net.collect_params().values()):
            with autograd.pause(train_mode=True):
                self.net.forward(*arrs[:n_net_inputs])
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        if not trainer._states_initialized:
            trainer._init_states()

        if self._model_id is None:
            self._model_id = aot.model_id_for(
                self.net,
                extra=("train", type(self.trainer._optimizer).__name__,
                       type(self.loss_fn).__name__))
        # the instance token lives in the KEY, not the model_id, and is
        # applied even to an explicit model_id: train entries carry this
        # instance's param/aux NDArray lists, so two TrainSteps must never
        # share one (a hit would silently train the builder's net)
        cache_key = aot.cache_key(
            self._model_id,
            tuple((a.shape, str(a.dtype)) for a in arrs),
            kind="train", mesh=aot.mesh_sig(self.mesh),
            extra=(n_net_inputs, "i%x" % id(self)))
        step_t0 = _time.perf_counter()
        # the per-step RNG key is drawn BEFORE the build so a compile
        # miss can shape its arg specs from it (one draw per step either
        # way — only the draw's position moved)
        key = _rnd._next_key()
        entry = aot.CACHE.lookup(cache_key)
        compile_miss = entry is None
        flightrec.record("step_begin", step=self._step_count + 1,
                         compile=compile_miss)
        if compile_miss:
            flightrec.record("compile_begin", kind="train")
            # Single-device train programs AOT-compile inside this build
            # span (jit().lower(arg_specs).compile() in _build_entry) so
            # the entry is an analyzable compiled program; the mesh-train
            # wrapper (and any spec-construction failure) still
            # jax.jit-compiles LAZILY inside the first train:dispatch
            # (donated-buffer programs are never jax.export-persisted
            # either way). The retroactive train:compile span below
            # covers the whole trace+compile+first-run window (same
            # definition as the mxtpu_jit_compile_seconds_total counter),
            # which is what separates "slow step" from "recompiling
            # every step".
            arg_specs = self._arg_specs(arrs, key)
            with spans.span("train:build"):
                entry = aot.compile_cached(
                    cache_key,
                    lambda: self._build_entry(n_net_inputs, arg_specs))
                self._cache_keys.add(cache_key)
        jitted = entry.fn
        self._last_stats = entry.stats
        trainable, frozen, t_arrs, f_arrs, aux_box = entry.extras

        optimizer = trainer._optimizer
        # python-side schedule state (lr scheduler, update counts) advances here
        self._step_count += 1
        lrs, wds = [], []
        for i, p in enumerate(trainable):
            idx = trainer._param2idx.get(p.name, i)
            optimizer._update_count(idx)
            lrs.append(optimizer._get_lr(idx))
            wds.append(optimizer._get_wd(idx))
        t = self._step_count
        rescale = optimizer.rescale_grad / batch_size

        opt_states = []
        for i, p in enumerate(trainable):
            idx = trainer._param2idx.get(p.name, i)
            opt_states.append(_tree_to_data(trainer._states[idx]))

        # the whole dispatch + write-back holds the net's trace lock: a
        # mesh-path MISS dispatch IS the lazy train trace (inner swaps
        # tracers into the live param NDArrays), a HIT dispatch reads and
        # then writes those same ``_data`` slots — either interleaved
        # with a concurrent eval/warm trace of this net would capture
        # tracers or lose the step's update to the trace's
        # finally-restore. Uncontended (the common case: nothing else
        # traces this net) the RLock costs sub-µs per step.
        with spans.span("train:dispatch", compile=compile_miss), \
                self._trace_lock:
            dispatch_t0 = _time.perf_counter()
            loss_full, new_t, new_opt, aux_vals = jitted(
                [a._data for a in t_arrs], [a._data for a in f_arrs],
                opt_states, [a._data for a in arrs], key,
                jnp.asarray(lrs, jnp.float32), jnp.asarray(wds, jnp.float32),
                jnp.asarray(t, jnp.int32), jnp.asarray(rescale, jnp.float32))
            if entry.stats is not None:
                # device-truth MFU: opt-in sync (the block defeats
                # donated-buffer step chaining — docs/OBSERVABILITY.md);
                # unsynced, the observed span is the host dispatch window
                # and the rolling train MFU can read high while steps
                # pipeline
                if config.get_env("MXTPU_DEVSTATS_TRAIN_SYNC"):
                    try:
                        jax.block_until_ready(loss_full)
                    except Exception:
                        pass
                devstats.observe_dispatch(
                    "train", entry.stats,
                    _time.perf_counter() - dispatch_t0,
                    model=self._model_id)

            for a, d in zip(t_arrs, new_t):
                a._data = d
            for i, p in enumerate(trainable):
                idx = trainer._param2idx.get(p.name, i)
                trainer._states[idx] = _rewrap_state(trainer._states[idx],
                                                     new_opt[i])
            for a, v in zip(aux_box, aux_vals):
                a._data = v
        # numerics sentinel (stride-sampled, default off): on-device
        # stats taps over the per-sample loss and the updated parameter
        # tree — grads are fused inside the step program, so a NaN storm
        # in them surfaces here as non-finite loss/updates. tap() never
        # raises and costs a dict increment when unsampled.
        numwatch.tap(self._model_id, "train:loss", (loss_full,))
        numwatch.tap(self._model_id, "train:params", new_t)
        step_dur = _time.perf_counter() - step_t0
        _STEP_SECONDS.observe(step_dur)
        _STEPS.inc()
        _EXAMPLES.inc(int(batch_size))
        if compile_miss:
            _COMPILES.inc(kind="train")
            _COMPILE_SECONDS.inc(step_dur, kind="train")
            # retroactive: the compile window IS this whole cache-miss
            # step (trace + XLA compile + first run — see the lazy-compile
            # note above), emitted as a child of the open train:step span
            _record_compile_span("train:compile", step_dur)
            flightrec.record("compile_end", kind="train",
                             dur_s=round(step_dur, 6))
        flightrec.record("step_end", step=self._step_count,
                         dur_s=round(step_dur, 6))
        return NDArray(loss_full)


def _rewrap_state(old, new_data):
    """Write new jax arrays back into the existing NDArray state structure."""
    if old is None:
        return None
    if isinstance(old, NDArray):
        old._data = new_data
        return old
    if isinstance(old, (tuple, list)):
        return tuple(_rewrap_state(o, n) for o, n in zip(old, new_data))
    return new_data


class EvalStep:
    """Compiled inference step (train_mode=False): net(*inputs) in one
    program, dispatched through the process-wide aot.CACHE.

    The compiled program takes params as runtime inputs, so instances
    built on an identical model (aot.model_id_for content digest — or an
    explicit ``model_id``) SHARE executables: a hot-reloaded same-model
    version, a second BlockServable, or a second EvalStep never recompile
    a bucket this process already compiled. Misses use the explicit AOT
    pipeline (``jit(fn).lower(args).compile()``) so the XLA compile lands
    inside the eval:build span — never lazily inside a later dispatch —
    and the traced program is persisted via jax.export when
    MXTPU_AOT_CACHE_DIR is set, letting a fresh process load the
    executable instead of re-tracing the model (artifact hit, zero
    eval:compile spans).
    """

    def __init__(self, net, model_id=None):
        self.net = net
        self._model_id = model_id
        self._trace_lock = _net_trace_lock(net)
        self._pure = None       # (param_arrs, pure_fn): built once, no trace
        # device truth of the most recently dispatched program (aot entry
        # stats), None pre-dispatch — bench.py's cost-analysis MFU source
        self._last_stats = None

    def _ensure_pure(self):
        if self._pure is None:
            _params, param_arrs, pure_fn, _aux = \
                _functional.make_pure_fn(self.net, train_mode=False)
            self._pure = (param_arrs, pure_fn)
        return self._pure

    def _builder(self, arg_specs, persist):
        """aot.compile_cached build hook. With the artifact layer on
        (``persist``): trace ONCE via jax.export, AOT-compile the exported
        module, and hand the export back for persistence; with it off
        (MXTPU_AOT_CACHE_DIR unset — the default) go straight to the
        direct AOT pipeline and never pay the export round-trip for a
        file that would not be written. Compile-window metrics and the
        retroactive eval:compile span are emitted here so only the thread
        that actually built pays (and counts) the compile."""
        def build():
            t0 = _time.perf_counter()
            flightrec.record("compile_begin", kind="eval")
            # the net's trace lock is held EXCLUSIVELY for the whole
            # trace: the live params hold tracers until the export/lower
            # restores them, and no dispatch may capture _data meanwhile
            with spans.span("eval:build"), self._trace_lock:
                _param_arrs, pure_fn = self._ensure_pure()
                exported, fn = None, None
                if persist:
                    try:
                        # NB `from` form: a bare `import jax.export` here
                        # would make `jax` function-local and break the
                        # persist=False path below (UnboundLocalError)
                        from jax import export as jax_export
                        exported = jax_export.export(
                            jax.jit(pure_fn))(*arg_specs)
                        fn = jax.jit(exported.call).lower(
                            *arg_specs).compile()
                    except Exception:
                        # non-exportable program (custom calls, platform
                        # quirks): fall back to direct AOT compile,
                        # in-memory only — the drop must be diagnosable
                        _LOG.debug("jax.export failed; eval program stays "
                                   "in-memory", exc_info=True)
                        exported = None
                if fn is None:
                    fn = jax.jit(pure_fn).lower(*arg_specs).compile()
            compile_dur = _time.perf_counter() - t0
            _COMPILES.inc(kind="eval")
            _COMPILE_SECONDS.inc(compile_dur, kind="eval")
            _record_compile_span("eval:compile", compile_dur)
            flightrec.record("compile_end", kind="eval",
                             dur_s=round(compile_dur, 6))
            return fn, None, exported
        return build

    def __call__(self, *inputs):
        arrs = [a if isinstance(a, NDArray) else NDArray(jnp.asarray(a)) for a in inputs]
        if self._model_id is None:
            self._model_id = aot.model_id_for(self.net, extra=("eval",))
        cache_key = aot.cache_key(self._model_id, aot.input_signature(arrs),
                                  kind="eval")
        key = jax.random.PRNGKey(0)
        entry = aot.CACHE.lookup(cache_key)
        compile_miss = entry is None
        if compile_miss:
            param_arrs, _pure_fn = self._ensure_pure()
            arg_specs = (
                [jax.ShapeDtypeStruct(a._data.shape, a._data.dtype)
                 for a in param_arrs],
                [jax.ShapeDtypeStruct(a._data.shape, a._data.dtype)
                 for a in arrs],
                key)
            persist = aot.artifact_path(cache_key) is not None
            entry = aot.compile_cached(cache_key,
                                       self._builder(arg_specs, persist),
                                       exportable=persist,
                                       arg_specs=arg_specs)
            # an artifact load is NOT a compile: no trace happened, no
            # eval:compile span was recorded, the compile counter is
            # untouched — the dispatch below is an ordinary warm step
            compile_miss = entry.source == "build"
        else:
            param_arrs, _pure_fn = self._ensure_pure()
        # capture the param snapshot under the net's trace lock (a
        # concurrent trace of ANOTHER bucket has tracers swapped into
        # these NDArrays for its whole window; sub-µs when uncontended),
        # then execute outside it — captured real arrays can't be
        # corrupted by a trace that starts later
        with self._trace_lock:
            param_datas = [a._data for a in param_arrs]
        self._last_stats = entry.stats
        # the device leg of the serving span chain: under the batcher this
        # nests inside the worker's serve:batch span (same thread)
        with spans.span("eval:step", compile=compile_miss):
            dispatch_t0 = _time.perf_counter()
            out_datas, _aux = entry.fn(param_datas,
                                       [a._data for a in arrs], key)
            # MFU observation needs a block-until-ready span (device
            # time, not enqueue time). Under the batcher (an ambient
            # dispatch context) the very next step is a host
            # materialization anyway, so the sync moves cost rather than
            # adding any — always observe there. STANDALONE eval loops
            # overlap host prep with device execution, and an
            # unconditional block would serialize them: opt in via
            # MXTPU_DEVSTATS_EVAL_SYNC (mirror of the train knob).
            if entry.stats is not None and (
                    devstats.in_dispatch_context()
                    or config.get_env("MXTPU_DEVSTATS_EVAL_SYNC")):
                try:
                    jax.block_until_ready(out_datas)
                except Exception:
                    pass
                devstats.observe_dispatch(
                    "eval", entry.stats,
                    _time.perf_counter() - dispatch_t0,
                    model=self._model_id)
        outs = [NDArray(o) for o in out_datas]
        return outs[0] if len(outs) == 1 else tuple(outs)
