"""Gluon — imperative NN API (ref python/mxnet/gluon/__init__.py)."""
from .block import Block, HybridBlock, SymbolBlock  # noqa
from .parameter import Parameter, Constant, ParameterDict, DeferredInitializationError  # noqa
from .trainer import Trainer  # noqa
from . import nn  # noqa
from . import loss  # noqa
from . import data  # noqa
from . import rnn  # noqa
from . import model_zoo  # noqa
from . import contrib  # noqa
from .utils import split_data, split_and_load, clip_global_norm  # noqa
