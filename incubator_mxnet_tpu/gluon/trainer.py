"""Gluon Trainer (ref python/mxnet/gluon/trainer.py:28).

Reference parity: kvstore wiring (:182-270), ``step`` (:328),
``_allreduce_grads`` (:379), ``_update`` (:438), save/load_states (:471,500).

TPU-native design: with a single logical parameter copy, ``_allreduce_grads``
is a no-op locally (SPMD data-parallel gradients are psum'd *inside* the
compiled step by parallel.DataParallelTrainer); the kvstore facade is kept for
API compatibility and server-style update_on_kvstore flows.
"""
from __future__ import annotations

from .. import optimizer as opt
from .. import kvstore as kvs_mod
from ..ndarray import NDArray
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values()) if hasattr(params, "values") else list(params)
        if not isinstance(params, (list, tuple)):
            raise ValueError("First argument must be a list or dict of Parameters")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError("First argument must contain Parameters, got %s" % type(param))
            self._params.append(param)
            self._param2idx[param.name] = i
        self._compression_params = compression_params
        self._contexts = self._check_contexts()
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._scale = self._optimizer.rescale_grad
        self._kvstore_params = {"kvstore": kvstore,
                                "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._states = [None] * len(self._params)
        self._states_initialized = False

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx() if param._data is not None or param._ctx else None
            contexts = contexts or ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        if kvstore is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = kvs_mod.create(kvstore) if isinstance(kvstore, str) else kvstore
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            update_on_kvstore = config["update_on_kvstore"]
            if update_on_kvstore is None:
                update_on_kvstore = kv.type.startswith("dist")
            self._update_on_kvstore = update_on_kvstore
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    kv.init(i, param.data())
        self._kv_initialized = True

    # ------------------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _init_states(self):
        for i, param in enumerate(self._params):
            if param.grad_req != "null" and self._states[i] is None:
                self._states[i] = self._optimizer.create_state_multi_precision(
                    i, param.data())
        self._states_initialized = True

    # ------------------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """rescale, allreduce, update (ref trainer.py:328)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if not self._states_initialized and not self._update_on_kvstore:
            self._init_states()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        """ref trainer.py:379. Single-logical-copy: kvstore push/pull only
        matters for update_on_kvstore (server-style) flows."""
        if self._kvstore is None or not self._update_on_kvstore:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kvstore.push(i, param.grad(), priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if not self._states_initialized and not self._update_on_kvstore:
            self._init_states()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._update_on_kvstore and self._kvstore is not None:
                self._kvstore.pull(i, param.data(), priority=-i)
                continue
            new_state = self._optimizer.update_multi_precision(
                i, param.data(), param.grad(), self._states[i])
            if new_state is not None:
                self._states[i] = new_state

    # ------------------------------------------------------------------
    def save_states(self, fname):
        """ref trainer.py:471."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=False)
            return
        if not self._states_initialized:
            self._init_states()
        updater = opt.Updater(self._optimizer)
        updater.states = {i: s for i, s in enumerate(self._states) if s is not None}
        with open(fname, "wb") as f:
            f.write(updater.get_states(dump_optimizer=False))

    def load_states(self, fname):
        """ref trainer.py:500."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        updater = opt.Updater(self._optimizer)
        with open(fname, "rb") as f:
            updater.set_states(f.read())
        for i, s in updater.states.items():
            self._states[int(i)] = s
        self._states_initialized = True
