"""Block / HybridBlock — the imperative NN API (ref python/mxnet/gluon/block.py:229,827).

TPU-native design: ``hybridize()`` does NOT build an NNVM graph — it wraps the
whole forward into ONE pure JAX function compiled by jax.jit (the CachedOp and
GraphExecutor of the reference collapse into this single compile-and-cache
component, SURVEY §7 table). Under autograd.record the compiled call is taped
as a single entry whose VJP is the XLA-differentiated whole graph.
"""
from __future__ import annotations

import os
import re
import threading

import jax
import numpy as onp

from .. import autograd
from .. import ndarray as nd
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError
from . import _functional

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name scoping for parameter prefixes (ref block.py:35 _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name_counter(hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params, None
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params, current._block._scope

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *a):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


_NAME_COUNTERS = {}


def _name_counter(hint):
    count = _NAME_COUNTERS.get(hint, 0)
    _NAME_COUNTERS[hint] = count + 1
    return "%s%d" % (hint, count)


class HookHandle:
    """Detachable hook registration (ref python/mxnet/gluon/utils.py HookHandle)."""

    def __init__(self, hooks_list, hook):
        self._list = hooks_list
        self._hook = hook

    def detach(self):
        if self._hook in self._list:
            self._list.remove(self._hook)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.detach()


class Block:
    """Base building block (ref gluon/block.py:229)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params, self._scope_parent = _BlockScope.create(
            prefix, params, self._alias())
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._prefix[:-1] if self._prefix.endswith("_") else self._prefix

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        """Returns a detachable handle (ref block.py HookHandle)."""
        self._forward_hooks.append(hook)
        return HookHandle(self._forward_hooks, hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)
        return HookHandle(self._forward_pre_hooks, hook)

    def collect_params(self, select=None):
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({n: p for n, p in self.params.items() if pattern.match(n)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, p in self.params.items():
            p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # -- persistence ---------------------------------------------------
    def save_parameters(self, filename, deduplicate=False):
        """ref gluon/block.py:417."""
        params = self._collect_params_with_prefix()
        nd.save(filename, {k: v.data() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):
        """ref gluon/block.py:473."""
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise AssertionError("Parameter %s missing in %s" % (name, filename))
        for name, data in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise AssertionError("Parameter %s in file not found in Block" % name)
                continue
            p = params[name]
            if p._data is None:
                p.shape = data.shape
                if p._deferred_init is not None:
                    p._finish_deferred_init()
                else:
                    p.initialize(ctx=ctx)
            p.set_data(data)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # -- call ----------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        if args and all(isinstance(a, NDArray) for a in args):
            # remember the input SIGNATURE (shape/dtype only — keeping the
            # live arrays would pin the batch's device buffers in HBM) so
            # export() can emit the serving artifact without an explicit
            # example (see HybridBlock.export)
            self._last_input_avals = [(a.shape, a.dtype) for a in args]
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        summary_lines = ["-" * 64, "%-30s %20s" % ("Layer (type)", "Output Shape"),
                        "=" * 64]
        def walk(block, x, depth=0):
            out = block(x)
            return out
        out = self(*inputs)
        summary_lines.append("%-30s %20s" % (self.name, getattr(out, "shape", "?")))
        print("\n".join(summary_lines))

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(repr(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)


def _indent(s_, num_spaces):
    lines = s_.split("\n")
    if len(lines) == 1:
        return s_
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


class HybridBlock(Block):
    """Block that can be compiled to one XLA program (ref block.py:827).

    Subclasses implement ``hybrid_forward(F, x, **params)`` (MXNet idiom) or
    plain ``forward(x)``.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)
        self._active = False
        self._cached_fn = None
        self._cached_meta = None
        self._flags = {}

    def hybridize(self, active=True, **kwargs):
        """Compile forward with jax.jit (≙ CachedOp, cached_op.cc:762)."""
        self._active = active
        self._flags = kwargs
        self._cached_fn = None
        for child in self._children.values():
            if isinstance(child, HybridBlock):
                # only the outermost compiled scope matters; children run traced
                child._flags = kwargs

    def infer_shape(self, *args):
        self._deferred_infer_shape(*args)

    def _deferred_infer_shape(self, *args):
        # run one eager forward on zeros to trigger deferred param init
        with autograd.pause():
            self.forward(*args)

    def cast(self, dtype):
        self._cached_fn = None
        super().cast(dtype)

    # -- hybrid_forward adapter ---------------------------------------
    def forward(self, *args):
        """Default: adapt MXNet's hybrid_forward(F, x, **params) signature."""
        if type(self).hybrid_forward is not HybridBlock.hybrid_forward:
            kwargs = {}
            for name, param in self._reg_params.items():
                try:
                    kwargs[name] = param.data()
                except DeferredInitializationError:
                    self._infer_param_shapes(*args)
                    kwargs[name] = param.data()
            return self.hybrid_forward(nd, *args, **kwargs)
        raise NotImplementedError(
            "%s must implement forward or hybrid_forward" % type(self).__name__)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _infer_param_shapes(self, *args):
        """Infer deferred shapes from inputs (layer-specific override)."""
        raise DeferredInitializationError(
            "%s has uninitialized parameters and no shape inference; "
            "initialize with known in_units/in_channels" % type(self).__name__)

    # -- compiled call -------------------------------------------------
    def __call__(self, *args):
        if not self._active:
            return super().__call__(*args)
        return self._call_cached(*args)

    def _call_cached(self, *args):
        train_mode = autograd.is_training()
        arg_arrays = [a if isinstance(a, NDArray) else nd.array(a) for a in args]

        # deferred init: run shapes through eager path once
        try:
            params = list(self.collect_params().values())
            for p in params:
                if p._data is None and p._deferred_init is not None:
                    with autograd.pause(train_mode=train_mode):
                        Block.__call__(self, *arg_arrays)
                    break
        except DeferredInitializationError:
            pass

        meta = (train_mode, tuple((a.shape, str(a.dtype)) for a in arg_arrays))
        # per-net trace-lock discipline (jit._net_trace_lock): this path's
        # lazy first call TRACES pure_fn inside nd._apply — swapping
        # tracers into the live param NDArrays — and its hit path reads
        # a._data; either concurrent with an EvalStep/TrainStep/prewarm
        # trace of the same net would capture tracers mid-swap. Held for
        # the whole lookup+apply (dispatch is async; sub-µs uncontended).
        from .. import jit as _jit
        with _jit._net_trace_lock(self):
            return self._call_cached_locked(meta, train_mode, arg_arrays)

    def _call_cached_locked(self, meta, train_mode, arg_arrays):
        if self._cached_fn is None:
            self._cached_fn = {}
        if meta in self._cached_fn:
            # LRU touch (evict_to_bound contract): move-to-end so the
            # bound drops the coldest shape, never the one dispatching now
            self._cached_fn[meta] = self._cached_fn.pop(meta)
        else:
            params, param_arrs, pure_fn, aux_box = _functional.make_pure_fn(
                self, train_mode)
            jitted = jax.jit(lambda pd, xd, key: pure_fn(pd, xd, key))
            self._cached_fn[meta] = (jitted, param_arrs, aux_box)
            from ..config import evict_to_bound
            evict_to_bound(self._cached_fn)
        jitted, param_arrs, aux_box = self._cached_fn[meta]

        key = jax.random.PRNGKey(0) if not train_mode else _split_global_key()

        def taped_fn(*flat):
            n = len(param_arrs)
            pd, xd = list(flat[:n]), list(flat[n:])
            out_datas, aux_vals = jitted(pd, xd, key)
            return tuple(out_datas) + tuple(aux_vals)

        all_inputs = param_arrs + arg_arrays
        results = nd._apply(taped_fn, *all_inputs)
        if not isinstance(results, (tuple, list)):
            results = (results,)
        n_aux = len(aux_box)
        outs = list(results[: len(results) - n_aux])
        aux_new = results[len(results) - n_aux:]
        with autograd.pause():
            for arr, new in zip(aux_box, aux_new):
                arr._data = new._data
        return outs[0] if len(outs) == 1 else tuple(outs)

    def export(self, path, epoch=0, example_inputs=None):
        """Export for deployment (ref block.py:1106 HybridBlock.export).

        TPU-native: saves parameters + a manifest JSON, and — when the
        input signature is known (``example_inputs`` given, or the block
        has been called) — a ``<path>.mxtpu`` serving artifact (serialized
        compiled StableHLO, contrib/serving.py). ``SymbolBlock.imports``
        on the manifest loads that artifact back as an inference block, so
        export → imports round-trips like the reference's symbol.json +
        params contract.
        """
        import json
        params = self._collect_params_with_prefix()
        nd.save("%s-%04d.params" % (path, epoch),
                {("arg:" + k): v.data() for k, v in params.items()})
        artifact = None
        inputs = example_inputs
        if inputs is None:
            avals = getattr(self, "_last_input_avals", None)
            if avals is not None:
                inputs = [nd.zeros(shape, dtype=dtype)
                          for shape, dtype in avals]
        if inputs is not None:
            from ..contrib import serving
            artifact = "%s.mxtpu" % path
            serving.export_model(self, inputs, artifact)
        with open("%s-symbol.json" % path, "w") as f:
            json.dump({"format": "incubator_mxnet_tpu.hybrid",
                       "class": type(self).__name__,
                       "artifact": artifact and os.path.basename(artifact)},
                      f)


def _split_global_key():
    from ..ndarray import random as _rnd
    return _rnd._next_key()


class SymbolBlock(HybridBlock):
    """Wrap a Symbol graph as a Block (ref block.py:1218)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol import Symbol
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = outputs if isinstance(outputs, Symbol) else outputs[0]
        self._sym = out
        input_names = {i.name for i in self._inputs}
        for name in out.list_inputs():
            if name not in input_names:
                self.params.get(name, allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load a serialized graph (+.params) as a Block
        (ref block.py:1311 SymbolBlock.imports). Accepts either a real
        Symbol graph JSON or a HybridBlock.export manifest — the latter
        loads the exported ``.mxtpu`` serving artifact as an
        inference-only block (params are baked into the program)."""
        import json as _json
        from .. import symbol as mxsym
        with open(symbol_file) as f:
            head = f.read(4096)
        try:
            meta = _json.loads(head)
        except ValueError:
            meta = None
        if isinstance(meta, dict) and \
                meta.get("format") == "incubator_mxnet_tpu.hybrid":
            artifact = meta.get("artifact")
            if not artifact:
                raise ValueError(
                    "%s is a hybrid-export manifest without a serving "
                    "artifact; re-export after a forward pass (or with "
                    "example_inputs) so the .mxtpu program is written"
                    % symbol_file)
            apath = os.path.join(os.path.dirname(os.path.abspath(symbol_file)),
                                 artifact)
            return _ServedBlock(apath)
        sym = mxsym.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        name_to_var = {v.name: v for v in sym.get_internals() if v.is_var}
        inputs = [name_to_var[n] for n in input_names]
        block = SymbolBlock(sym, inputs)
        if param_file is not None:
            loaded = {k.split(":", 1)[-1]: v  # strip arg:/aux: prefixes
                      for k, v in nd.load(param_file).items()}
            extra = set(loaded) - set(block.params.keys())
            missing = set(block.params.keys()) - set(loaded)
            if extra or missing:
                raise AssertionError(
                    "params file does not match the graph: missing %s, "
                    "extra %s" % (sorted(missing), sorted(extra)))
            for name, v in loaded.items():
                p = block.params.get(name)
                p.shape = tuple(v.shape)
                p.initialize(init="zeros", ctx=ctx, force_reinit=True)
                p.set_data(v if ctx is None else v.as_in_context(ctx))
        return block

    def forward(self, *args):
        bindings = {i.name: a for i, a in zip(self._inputs, args)}
        for name, p in self.params.items():
            bindings[name] = p.data()
        return self._sym.eval_imperative(bindings)


class _ServedBlock(Block):
    """SymbolBlock.imports result for hybrid-export manifests: wraps the
    .mxtpu serving artifact (compiled program, params baked in) as an
    inference-only Block."""

    def __init__(self, artifact_path):
        super().__init__(prefix="", params=None)
        from ..contrib import serving
        self._served = serving.load(artifact_path)
        self._artifact_path = artifact_path

    def forward(self, *args):
        return self._served.predict(*args)
