"""gluon.nn namespace (ref python/mxnet/gluon/nn/__init__.py)."""
from .basic_layers import *  # noqa
from .conv_layers import *  # noqa
from ..block import Block, HybridBlock, SymbolBlock  # noqa
