"""Convolution & pooling layers (ref python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

from ... import ndarray as nd
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D", "AvgPool1D",
           "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D", "GlobalMaxPool2D",
           "GlobalMaxPool3D", "GlobalAvgPool1D", "GlobalAvgPool2D", "GlobalAvgPool3D",
           "ReflectionPad2D"]


def _pair(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    """Shared conv implementation (ref conv_layers.py _Conv → nn/convolution-inl.h)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation, groups,
                 layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", op_name="Convolution",
                 adj=None, prefix=None, params=None):
        super().__init__(prefix, params)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        self.act_type = activation
        with self.name_scope():
            if op_name == "Convolution":
                wshape = (channels, in_channels // groups if in_channels else 0) + tuple(kernel_size)
            else:  # Deconvolution weight is (in, out//groups, *k)
                wshape = (in_channels, channels // groups if channels else 0) + tuple(kernel_size)
            self.weight = self.params.get("weight", shape=wshape,
                                          init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(channels,),
                                            init=bias_initializer,
                                            allow_deferred_init=True)
            else:
                self.bias = None

    def forward(self, x):
        if self.weight._data is None:
            in_c = x.shape[1]
            ws = list(self.weight.shape)
            if self._op_name == "Convolution":
                ws[1] = in_c // self._kwargs["num_group"]
            else:
                ws[0] = in_c
                if ws[1] == 0:
                    ws[1] = self._channels // self._kwargs["num_group"]
            self.weight.shape = tuple(ws)
            self.weight._finish_deferred_init()
            if self.bias is not None:
                self.bias._finish_deferred_init()
        op = getattr(nd, self._op_name)
        out = op(x, self.weight.data(),
                 self.bias.data() if self.bias is not None else None,
                 no_bias=self.bias is None, **self._kwargs)
        if self.act_type:
            out = nd.Activation(out, act_type=self.act_type)
        return out

    def __repr__(self):
        return "%s(channels=%d, kernel=%s)" % (
            type(self).__name__, self._channels, self._kwargs["kernel"])


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kw)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kw):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kw)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kw):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, **kw)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, output_padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_pair(output_padding, 1), **kw)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1, layout="NCHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_pair(output_padding, 2), **kw)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1), padding=(0, 0, 0),
                 output_padding=(0, 0, 0), dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kw):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_pair(output_padding, 3), **kw)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def forward(self, x):
        return nd.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "%s(size=%s)" % (type(self).__name__, self._kwargs["kernel"])


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kw):
        super().__init__(_pair(pool_size, 1), _pair(strides, 1) if strides else None,
                         _pair(padding, 1), ceil_mode, False, "max", layout, **kw)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kw):
        super().__init__(_pair(pool_size, 2), _pair(strides, 2) if strides else None,
                         _pair(padding, 2), ceil_mode, False, "max", layout, **kw)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW",
                 ceil_mode=False, **kw):
        super().__init__(_pair(pool_size, 3), _pair(strides, 3) if strides else None,
                         _pair(padding, 3), ceil_mode, False, "max", layout, **kw)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kw):
        super().__init__(_pair(pool_size, 1), _pair(strides, 1) if strides else None,
                         _pair(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad, **kw)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, count_include_pad=True, **kw):
        super().__init__(_pair(pool_size, 2), _pair(strides, 2) if strides else None,
                         _pair(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad, **kw)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0, layout="NCDHW",
                 ceil_mode=False, count_include_pad=True, **kw):
        super().__init__(_pair(pool_size, 3), _pair(strides, 3) if strides else None,
                         _pair(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad, **kw)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kw):
        super().__init__((1,), None, (0,), True, True, "max", layout, **kw)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kw):
        super().__init__((1, 1), None, (0, 0), True, True, "max", layout, **kw)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kw):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "max", layout, **kw)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kw):
        super().__init__((1,), None, (0,), True, True, "avg", layout, **kw)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kw):
        super().__init__((1, 1), None, (0, 0), True, True, "avg", layout, **kw)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kw):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "avg", layout, **kw)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def forward(self, x):
        return nd.pad(x, mode="reflect", pad_width=self._padding)
