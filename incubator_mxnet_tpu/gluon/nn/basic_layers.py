"""Basic neural-net layers (ref python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray
from ..block import Block, HybridBlock
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "SyncBatchNorm", "InstanceNorm", "LayerNorm", "GroupNorm", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU", "ELU",
           "SELU", "Swish", "GELU", "Identity"]


class Sequential(Block):
    """ref basic_layers.py Sequential."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """ref basic_layers.py HybridSequential — one fused XLA program when hybridized."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix, params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer (ref basic_layers.py Dense → nn/fully_connected.cc)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, prefix=None, params=None):
        super().__init__(prefix, params)
        self._units = units
        self._flatten = flatten
        self.act_type = activation
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(units, in_units),
                                          init=weight_initializer, dtype=dtype,
                                          allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get("bias", shape=(units,),
                                            init=bias_initializer, dtype=dtype,
                                            allow_deferred_init=True)
            else:
                self.bias = None

    def forward(self, x):
        if self.weight._data is None:
            in_units = int(onp.prod(x.shape[1:])) if self._flatten else x.shape[-1]
            self.weight.shape = (self._units, in_units)
            self.weight._finish_deferred_init()
            if self.bias is not None:
                self.bias._finish_deferred_init()
        out = nd.FullyConnected(x, self.weight.data(),
                                self.bias.data() if self.bias is not None else None,
                                num_hidden=self._units, flatten=self._flatten,
                                no_bias=self.bias is None)
        if self.act_type:
            out = nd.Activation(out, act_type=self.act_type)
        return out

    def __repr__(self):
        return "Dense(%s -> %s)" % (self.weight.shape[1] or None, self._units)


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        return nd.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return "Dropout(p=%s)" % self._rate


class Embedding(HybridBlock):
    """ref basic_layers.py Embedding → tensor/indexing_op.cc."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(input_dim, output_dim),
                                          init=weight_initializer, dtype=dtype)

    def forward(self, x):
        return nd.Embedding(x, self.weight.data(), input_dim=self._input_dim,
                            output_dim=self._output_dim)

    def __repr__(self):
        return "Embedding(%d -> %d)" % (self._input_dim, self._output_dim)


class BatchNorm(HybridBlock):
    """ref basic_layers.py BatchNorm → nn/batch_norm.cc."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)
            self.running_mean = self.params.get("running_mean", grad_req="null",
                                                shape=(in_channels,),
                                                init=running_mean_initializer,
                                                allow_deferred_init=True,
                                                differentiable=False)
            self.running_var = self.params.get("running_var", grad_req="null",
                                               shape=(in_channels,),
                                               init=running_variance_initializer,
                                               allow_deferred_init=True,
                                               differentiable=False)

    def _ensure_init(self, x):
        if self.gamma._data is None:
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta, self.running_mean, self.running_var):
                p.shape = (c,)
                p._finish_deferred_init()

    def forward(self, x):
        self._ensure_init(x)
        return nd.BatchNorm(x, self.gamma.data(), self.beta.data(),
                            self.running_mean.data(), self.running_var.data(),
                            eps=self._epsilon, momentum=self._momentum,
                            fix_gamma=not self._scale,
                            use_global_stats=self._use_global_stats, axis=self._axis)

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # BN statistics stay fp32 (AMP semantics)
        super().cast(dtype)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def forward(self, x):
        if self.gamma._data is None:
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta):
                p.shape = (c,)
                p._finish_deferred_init()
        return nd.InstanceNorm(x, self.gamma.data(), self.beta.data(), eps=self._epsilon)


class LayerNorm(HybridBlock):
    """ref basic_layers.py LayerNorm → nn/layer_norm.cc."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def forward(self, x):
        if self.gamma._data is None:
            c = x.shape[self._axis]
            for p in (self.gamma, self.beta):
                p.shape = (c,)
                p._finish_deferred_init()
        return nd.LayerNorm(x, self.gamma.data(), self.beta.data(),
                            axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", grad_req="write" if scale else "null",
                                         shape=(in_channels,), init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", grad_req="write" if center else "null",
                                        shape=(in_channels,), init=beta_initializer,
                                        allow_deferred_init=True)

    def forward(self, x):
        if self.gamma._data is None:
            c = x.shape[1]
            for p in (self.gamma, self.beta):
                p.shape = (c,)
                p._finish_deferred_init()
        return nd.GroupNorm(x, self.gamma.data(), self.beta.data(),
                            num_groups=self._num_groups, eps=self._epsilon)


class Flatten(HybridBlock):
    def forward(self, x):
        return x.flatten()

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_impl = getattr(nd, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_impl = getattr(nd, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def forward(self, x):
        return nd.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "Activation(%s)" % self._act_type


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return nd.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer or initializer.Constant(0.25))

    def forward(self, x):
        return nd.LeakyReLU(x, gamma=self.alpha.data(), act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return nd.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return nd.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def forward(self, x):
        return nd.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def forward(self, x):
        return x * nd.sigmoid(self._beta * x)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm (ref gluon/contrib/nn/basic_layers.py
    SyncBatchNorm, src/operator/contrib/sync_batch_norm.cc).

    TPU-native: under SPMD data parallelism the fused train step computes
    batch statistics over the GLOBAL batch — ``jnp.mean`` along a dp-sharded
    axis lowers to a cross-device all-reduce — so BatchNorm is already
    synchronized; this subclass exists for API parity. ``num_devices`` is
    accepted and ignored (the mesh defines the sync group).
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)
