"""DataLoader (ref python/mxnet/gluon/data/dataloader.py:27-131).

Reference parity: batchify, samplers, num_workers. TPU-native design: worker
parallelism uses a thread pool feeding a double-buffered prefetch queue — the
analog of the reference's multiprocessing+shared-memory pipeline. Host→device
transfer overlaps with compute because jax.device_put is async. A C++
RecordIO/decode pipeline (native/) backs the heavy image path.
"""
from __future__ import annotations

import threading
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor
from queue import Queue

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (ref dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return nd.array(data)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=True,
                 timeout=120):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or last_batch:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._prefetch = max(0, int(prefetch) if prefetch is not None else 2 * num_workers)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._make_batch(batch)
            return
        # threaded pipeline with bounded prefetch (≙ PrefetcherIter double-buffer)
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = Queue()
            batches = iter(self._batch_sampler)
            stop = object()

            def submit_next():
                try:
                    b = next(batches)
                except StopIteration:
                    return False
                futures.put(pool.submit(self._make_batch, b))
                return True

            live = 0
            for _ in range(max(1, self._prefetch)):
                if submit_next():
                    live += 1
                else:
                    break
            while live:
                f = futures.get()
                live -= 1
                if submit_next():
                    live += 1
                yield f.result()

    def __len__(self):
        return len(self._batch_sampler)
