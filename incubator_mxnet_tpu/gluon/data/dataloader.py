"""DataLoader (ref python/mxnet/gluon/data/dataloader.py:27-131).

Reference parity: batchify, samplers, num_workers, process workers. Two
worker modes (selected by ``thread_pool`` like the reference):

- thread_pool=True: a thread pool feeds a bounded prefetch queue — cheap
  when __getitem__ releases the GIL (IO, native decode) or transforms are
  jax ops.
- thread_pool=False: spawned PROCESS workers (the reference's
  multiprocessing+shared-memory pipeline, dataloader.py:27-131). The
  dataset/batchify are pickled to each worker once; workers run pure
  numpy/PIL transforms GIL-free and return host batches the parent uploads.
  Workers force JAX_PLATFORMS=cpu and never touch the TPU (spawn, not fork:
  forking a process with live TPU handles is unsafe).

Host→device transfer overlaps with compute because jax.device_put is async.
A C++ RecordIO/decode pipeline (native/) backs the heavy image path.
"""
from __future__ import annotations

import pickle

from concurrent.futures import ThreadPoolExecutor
from queue import Queue

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]

_MP_DATASET = None
_MP_BATCHIFY = None


def _mp_init(ds_bytes, bf_bytes):
    import os
    # workers must come up clean on CPU — no TPU tunnel, no distributed init
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.pop("MXTPU_COORD_ADDR", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    global _MP_DATASET, _MP_BATCHIFY
    _MP_DATASET = pickle.loads(ds_bytes)
    _MP_BATCHIFY = pickle.loads(bf_bytes)


def _np_tree(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    if isinstance(x, dict):
        return {k: _np_tree(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return type(x)(_np_tree(i) for i in x)
    return onp.asarray(x)


def _mp_worker_fn(indices):
    batch = _MP_BATCHIFY([_MP_DATASET[i] for i in indices])
    return _np_tree(batch)  # host arrays cross the pipe; parent uploads


def _nd_tree(x):
    if isinstance(x, dict):
        return {k: _nd_tree(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return type(x)(_nd_tree(i) for i in x)
    return nd.array(x)


def default_batchify_fn(data):
    """Stack samples into a batch (ref dataloader.py default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = onp.asarray(data)
    return nd.array(data)


class DataLoader:
    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None, thread_pool=True,
                 timeout=120):
        self._mp_pool = None  # before any raise: __del__ reads it
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(sampler, batch_size, last_batch or "keep")
        elif batch_size is not None or shuffle or sampler is not None or last_batch:
            raise ValueError("batch_size, shuffle, sampler and last_batch must "
                             "not be specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = num_workers
        self._thread_pool = thread_pool
        self._timeout = timeout
        self._prefetch = max(0, int(prefetch) if prefetch is not None else 2 * num_workers)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def _get_mp_pool(self):
        if self._mp_pool is None:
            import multiprocessing
            import os
            ctx = multiprocessing.get_context("spawn")
            # spawn snapshots the PARENT env at Pool() time, and the package
            # __init__ the child imports (to unpickle) initializes TPU /
            # jax.distributed from these vars — sanitize BEFORE spawning,
            # restore after (the _mp_init cleanup would run too late)
            drop = ("MXTPU_COORD_ADDR", "MXTPU_NUM_PROC", "MXTPU_PROC_ID",
                    "PALLAS_AXON_POOL_IPS")
            saved = {k: os.environ.pop(k) for k in drop if k in os.environ}
            saved_jp = os.environ.get("JAX_PLATFORMS")
            os.environ["JAX_PLATFORMS"] = "cpu"
            try:
                self._mp_pool = ctx.Pool(
                    self._num_workers, initializer=_mp_init,
                    initargs=(pickle.dumps(self._dataset),
                              pickle.dumps(self._batchify_fn)))
            finally:
                os.environ.update(saved)
                if saved_jp is None:
                    os.environ.pop("JAX_PLATFORMS", None)
                else:
                    os.environ["JAX_PLATFORMS"] = saved_jp
        return self._mp_pool

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._make_batch(batch)
            return
        if not self._thread_pool:
            yield from self._iter_multiprocess()
            return
        # threaded pipeline with bounded prefetch (≙ PrefetcherIter double-buffer)
        with ThreadPoolExecutor(max_workers=self._num_workers) as pool:
            futures = Queue()
            batches = iter(self._batch_sampler)

            def submit_next():
                try:
                    b = next(batches)
                except StopIteration:
                    return False
                futures.put(pool.submit(self._make_batch, b))
                return True

            live = 0
            for _ in range(max(1, self._prefetch)):
                if submit_next():
                    live += 1
                else:
                    break
            while live:
                f = futures.get()
                live -= 1
                if submit_next():
                    live += 1
                yield f.result()

    def _iter_multiprocess(self):
        """Process workers: ordered async map with bounded in-flight window."""
        pool = self._get_mp_pool()
        batches = iter(self._batch_sampler)
        inflight = []

        def submit_next():
            try:
                b = next(batches)
            except StopIteration:
                return False
            inflight.append(pool.apply_async(_mp_worker_fn, (list(b),)))
            return True

        for _ in range(max(2, self._prefetch)):
            if not submit_next():
                break
        while inflight:
            res = inflight.pop(0)
            out = res.get(self._timeout)
            submit_next()
            yield _nd_tree(out)

    def __del__(self):
        if self._mp_pool is not None:
            try:
                self._mp_pool.terminate()
            except Exception:
                pass  # interpreter shutdown: pool internals already torn down

    def __len__(self):
        return len(self._batch_sampler)
