"""gluon.data (ref python/mxnet/gluon/data/__init__.py)."""
from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset  # noqa
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler  # noqa
from .dataloader import DataLoader, default_batchify_fn  # noqa
from . import vision  # noqa
from .vision import transforms  # noqa
