"""Vision datasets + transforms (ref python/mxnet/gluon/data/vision/).

Downloads are unavailable in this environment (zero egress); the standard
datasets read from a local root if present and otherwise generate a
deterministic synthetic substitute with the right shapes/classes so training
and tests run end-to-end.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as onp

from ... import ndarray as nd
from ...ndarray import NDArray
from .dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100", "ImageRecordDataset",
           "transforms"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._transform = transform
        self._train = train
        self._root = os.path.expanduser(root)
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)


def _synthetic(n, shape, num_classes, seed):
    # Class means come from a DEDICATED stream: train and test splits draw
    # different n, which used to shift the rng state before the means were
    # sampled — giving each split different class prototypes and making
    # held-out accuracy chance-level. Means are split-invariant now;
    # labels/noise still differ per split (keyed by n).
    base = onp.random.RandomState(seed).rand(num_classes, *shape) \
        .astype("float32")
    rng = onp.random.RandomState(seed + 100003 * n)
    label = rng.randint(0, num_classes, size=(n,)).astype("int32")
    data = base[label] * 0.8 + rng.rand(n, *shape).astype("float32") * 0.2
    return data, label


class MNIST(_DownloadedDataset):
    """ref gluon/data/vision/datasets.py MNIST (idx-gz format reader)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._num_synthetic = 8192 if train else 1024
        super().__init__(root, train, transform)

    def _get_data(self):
        prefix = "train" if self._train else "t10k"
        data_file = os.path.join(self._root, prefix + "-images-idx3-ubyte.gz")
        label_file = os.path.join(self._root, prefix + "-labels-idx1-ubyte.gz")
        if os.path.exists(data_file) and os.path.exists(label_file):
            with gzip.open(label_file, "rb") as fin:
                struct.unpack(">II", fin.read(8))
                label = onp.frombuffer(fin.read(), dtype=onp.uint8).astype(onp.int32)
            with gzip.open(data_file, "rb") as fin:
                struct.unpack(">IIII", fin.read(16))
                data = onp.frombuffer(fin.read(), dtype=onp.uint8)
                data = data.reshape(len(label), 28, 28, 1)
            self._data = nd.array(data, dtype="uint8")
            self._label = label
        else:
            data, label = _synthetic(self._num_synthetic, (28, 28, 1), 10, seed=42)
            self._data = nd.array((data * 255).astype("uint8"), dtype="uint8")
            self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._num_synthetic = 8192 if train else 1024
        self._num_classes = 10
        super().__init__(root, train, transform)

    def _get_data(self):
        data, label = _synthetic(self._num_synthetic, (32, 32, 3),
                                 self._num_classes, seed=1337)
        self._data = nd.array((data * 255).astype("uint8"), dtype="uint8")
        self._label = label


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._num_classes = 100
        _DownloadedDataset.__init__(self, root, train, transform)
        self._num_synthetic = 8192 if train else 1024


class ImageRecordDataset(Dataset):
    """Dataset over an image RecordIO file (ref vision/datasets.py)."""

    def __init__(self, filename, flag=1, transform=None):
        from ... import recordio, image
        self._record = recordio.MXIndexedRecordIO(
            filename[: filename.rfind(".")] + ".idx", filename, "r")
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ... import recordio, image
        record = self._record.read_idx(self._record.keys[idx])
        header, img = recordio.unpack(record)
        img = image.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self._record.keys)


# ---------------------------------------------------------------- transforms
class transforms:
    """Subset of gluon.data.vision.transforms as static callables."""

    class Compose:
        def __init__(self, transforms_list):
            self._ts = transforms_list

        def __call__(self, x, *args):
            for t in self._ts:
                x = t(x)
            return (x,) + args if args else x

    class ToTensor:
        """HWC uint8 -> CHW float32 /255 (ref transforms.ToTensor)."""

        def __call__(self, x, *args):
            if isinstance(x, NDArray):
                out = x.astype("float32").transpose((2, 0, 1)) / 255.0
            else:
                out = nd.array(onp.transpose(x, (2, 0, 1)).astype("float32") / 255.0)
            return (out,) + args if args else out

    class Normalize:
        def __init__(self, mean=0.0, std=1.0):
            self._mean = onp.asarray(mean, dtype="float32").reshape(-1, 1, 1)
            self._std = onp.asarray(std, dtype="float32").reshape(-1, 1, 1)

        def __call__(self, x, *args):
            out = (x - nd.array(self._mean)) / nd.array(self._std)
            return (out,) + args if args else out

    class Cast:
        def __init__(self, dtype="float32"):
            self._dtype = dtype

        def __call__(self, x, *args):
            out = x.astype(self._dtype)
            return (out,) + args if args else out

    class Resize:
        def __init__(self, size, keep_ratio=False, interpolation=1):
            self._size = (size, size) if isinstance(size, int) else tuple(size)

        def __call__(self, x, *args):
            import jax.image
            a = x._data if isinstance(x, NDArray) else onp.asarray(x)
            h, w = self._size[1], self._size[0]
            out = nd.NDArray(jax.image.resize(
                a.astype("float32"), (h, w, a.shape[2]), method="linear"
            ).astype(a.dtype))
            return (out,) + args if args else out

    class RandomFlipLeftRight:
        def __call__(self, x, *args):
            if onp.random.rand() < 0.5:
                x = x[:, ::-1, :] if not isinstance(x, NDArray) else nd.flip(x, 1)
            return (x,) + args if args else x

    class RandomFlipTopBottom:
        def __call__(self, x, *args):
            if onp.random.rand() < 0.5:
                x = x[::-1, :, :] if not isinstance(x, NDArray) else nd.flip(x, 0)
            return (x,) + args if args else x

    class CenterCrop:
        """ref transforms.CenterCrop — HWC center window (pads if smaller)."""

        def __init__(self, size):
            self._size = (size, size) if isinstance(size, int) else tuple(size)

        def __call__(self, x, *args):
            a = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            tw, th = self._size
            h, w = a.shape[:2]
            y0 = max(0, (h - th) // 2)
            x0 = max(0, (w - tw) // 2)
            out = a[y0:y0 + th, x0:x0 + tw]
            if out.shape[0] < th or out.shape[1] < tw:
                pad = onp.zeros((th, tw) + a.shape[2:], a.dtype)
                pad[:out.shape[0], :out.shape[1]] = out
                out = pad
            out = nd.array(out)
            return (out,) + args if args else out

    class RandomCrop:
        """ref transforms.RandomCrop — random HWC window (zero-pads edges)."""

        def __init__(self, size, pad=0):
            self._size = (size, size) if isinstance(size, int) else tuple(size)
            self._pad = pad

        def __call__(self, x, *args):
            a = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if self._pad:
                p = self._pad
                a = onp.pad(a, ((p, p), (p, p), (0, 0)))
            tw, th = self._size
            h, w = a.shape[:2]
            y0 = onp.random.randint(0, max(1, h - th + 1))
            x0 = onp.random.randint(0, max(1, w - tw + 1))
            out = nd.array(a[y0:y0 + th, x0:x0 + tw])
            return (out,) + args if args else out

    class RandomResizedCrop:
        """ref transforms.RandomResizedCrop — random area/ratio crop + resize."""

        def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
            self._size = (size, size) if isinstance(size, int) else tuple(size)
            self._scale = scale
            self._ratio = ratio

        def __call__(self, x, *args):
            import jax.image
            a = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            h, w = a.shape[:2]
            for _ in range(10):
                area = h * w * onp.random.uniform(*self._scale)
                ar = onp.random.uniform(*self._ratio)
                cw = int(round(onp.sqrt(area * ar)))
                ch = int(round(onp.sqrt(area / ar)))
                if cw <= w and ch <= h:
                    y0 = onp.random.randint(0, h - ch + 1)
                    x0 = onp.random.randint(0, w - cw + 1)
                    a = a[y0:y0 + ch, x0:x0 + cw]
                    break
            tw, th = self._size
            out = nd.NDArray(jax.image.resize(
                a.astype("float32"), (th, tw) + a.shape[2:],
                method="linear").astype(a.dtype))
            return (out,) + args if args else out

    class RandomBrightness:
        def __init__(self, brightness):
            self._b = brightness

        def __call__(self, x, *args):
            f = 1.0 + onp.random.uniform(-self._b, self._b)
            out = x * f
            return (out,) + args if args else out

    class RandomContrast:
        def __init__(self, contrast):
            self._c = contrast

        def __call__(self, x, *args):
            f = 1.0 + onp.random.uniform(-self._c, self._c)
            mean = float(nd.mean(_to_nd_img(x)).asnumpy())
            out = _to_nd_img(x) * f + mean * (1.0 - f)
            return (out,) + args if args else out

    class RandomSaturation:
        def __init__(self, saturation):
            self._s = saturation

        def __call__(self, x, *args):
            f = 1.0 + onp.random.uniform(-self._s, self._s)
            img = _to_nd_img(x)
            gray = nd.mean(img, axis=-1, keepdims=True)
            out = img * f + gray * (1.0 - f)
            return (out,) + args if args else out

    class RandomColorJitter:
        """brightness/contrast/saturation jitter in random order."""

        def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
            self._ts = []
            if brightness:
                self._ts.append(transforms.RandomBrightness(brightness))
            if contrast:
                self._ts.append(transforms.RandomContrast(contrast))
            if saturation:
                self._ts.append(transforms.RandomSaturation(saturation))

        def __call__(self, x, *args):
            order = onp.random.permutation(len(self._ts)) if self._ts else []
            for i in order:
                x = self._ts[i](x)
            return (x,) + args if args else x


def _to_nd_img(x):
    return x if isinstance(x, NDArray) else nd.array(onp.asarray(x))


class ImageFolderDataset(Dataset):
    """ref gluon/data/vision/datasets.py ImageFolderDataset: root/<class>/
    <image files>, labels from sorted class-folder names."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".bmp")):
                    self.items.append((os.path.join(path, fname), label))

    def __getitem__(self, idx):
        from ... import image as _image
        img = _image.imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
