"""Functional-mode machinery: turn a stateful Block call into a pure JAX
function — the TPU-native analog of CachedOp/hybridize
(ref src/imperative/cached_op.cc:762 Forward, python/mxnet/gluon/block.py:923).

In functional mode:
- Parameter data are temporarily swapped for traced values (the pure inputs).
- BatchNorm-style aux-state updates are COLLECTED (not written) and returned
  as extra outputs, then written back after the compiled call.
- Random ops draw from a per-call PRNG key argument instead of the global
  stateful key, so compiled programs get fresh randomness per step.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .. import autograd
from ..ndarray import NDArray


class _FnState(threading.local):
    def __init__(self):
        self.active = False
        self.key = None           # traced PRNG key, split per use
        self.aux_updates = None   # list of (Parameter, traced_new_value)


_STATE = _FnState()


def in_functional_mode():
    return _STATE.active


def next_functional_key():
    _STATE.key, sub = jax.random.split(_STATE.key)
    return sub


def collect_aux_update(param_arr, new_value):
    """Record 'param_arr should become new_value' instead of mutating (BatchNorm)."""
    _STATE.aux_updates.append((param_arr, new_value))


class FunctionalScope:
    def __init__(self, key):
        self._key = key

    def __enter__(self):
        self._prev = (_STATE.active, _STATE.key, _STATE.aux_updates)
        _STATE.active = True
        _STATE.key = self._key
        _STATE.aux_updates = []
        return _STATE

    def __exit__(self, *a):
        _STATE.active, _STATE.key, _STATE.aux_updates = self._prev


def make_pure_fn(block, train_mode):
    """Build fn(param_datas, input_datas, key) -> (out_datas, aux_new_values).

    ``aux_box`` (returned alongside) is filled at trace time with the live aux
    NDArrays, in the same order as aux_new_values — stable for a fixed graph.
    """
    params = list(block.collect_params().values())
    param_arrs = [p.data() for p in params]
    aux_box = []  # filled during trace: which NDArrays the aux outputs belong to

    def pure_fn(param_datas, input_datas, key):
        # swap traced data into the live NDArray objects
        saved = [a._data for a in param_arrs]
        for a, d in zip(param_arrs, param_datas):
            a._data = d
        try:
            with FunctionalScope(key) as st:
                with autograd.pause(train_mode=train_mode):
                    out = block.forward(*[NDArray(d) for d in input_datas])
                outs = out if isinstance(out, (list, tuple)) else [out]
                out_datas = [o._data for o in outs]
                aux_pairs = list(st.aux_updates)
        finally:
            for a, s in zip(param_arrs, saved):
                a._data = s
        aux_box[:] = [a for (a, _v) in aux_pairs]
        return out_datas, [v for (_a, v) in aux_pairs]

    return params, param_arrs, pure_fn, aux_box
