"""Parameter & ParameterDict (ref python/mxnet/gluon/parameter.py).

Reference parity: deferred shape init, grad_req, lr_mult/wd_mult,
initialize/reset_ctx/cast, save/load. TPU-native difference: a parameter holds
ONE logical copy (optionally sharded over a jax Mesh via its ``sharding``
attribute) instead of one replica per GPU context — replication is an SPMD
sharding decision, not a storage layout (SURVEY §2.5 north star).
"""
from __future__ import annotations

import re

import numpy as onp

from .. import autograd, initializer as init_mod
from .. import ndarray as nd
from ..context import Context, cpu, current_context
from ..ndarray import NDArray

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(Exception):
    """Parameter used before its shape was known (ref parameter.py:36)."""


class Parameter:
    """A trainable parameter (ref gluon/parameter.py Parameter)."""

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data = None          # NDArray
        self._grad = None          # NDArray
        self._deferred_init = None  # (initializer, ctx, default_init)
        self._ctx = None
        self.sharding = None       # optional jax.sharding spec for SPMD layouts

    # ----------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        unknown_ok = all(s1 == s2 or s1 in (0, -1, None)
                         for s1, s2 in zip(self._shape, new_shape))
        if not unknown_ok or len(self._shape) != len(new_shape):
            raise ValueError("Cannot overwrite shape %s with %s for Parameter %s"
                             % (self._shape, new_shape, self.name))
        self._shape = tuple(new_shape)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null")
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                self._data.grad_buf = None
        elif self._data is not None and self._grad is None:
            self._init_grad()

    def _shape_known(self):
        return self._shape is not None and all(s > 0 for s in self._shape)

    # ----------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx = list(ctx)
        if not self._shape_known():
            if self.allow_deferred_init:
                self._deferred_init = (init, self._ctx, default_init)
                return
            raise ValueError(
                "Cannot initialize Parameter %s because it has invalid shape %s."
                % (self.name, self._shape))
        self._finish_init(init, default_init)

    def _finish_init(self, init, default_init):
        data = nd.zeros(self._shape, ctx=self._ctx[0] if self._ctx else None,
                        dtype=self.dtype)
        initializer = self.init if init is None else init
        if initializer is None:
            default_init(self.name, data)
        else:
            # explicit per-parameter init bypasses name-suffix dispatch
            # (ref initializer.py __call__: attrs['__init__'] → _init_weight)
            if isinstance(initializer, str):
                initializer = init_mod.create(initializer)
            if isinstance(initializer, init_mod.Initializer):
                initializer._init_weight(self.name, data)
            else:
                initializer(self.name, data)
        if data.dtype != nd._np_dtype(self.dtype):
            data = data.astype(self.dtype)
        self._data = data
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        if not self._shape_known():
            raise DeferredInitializationError(
                "Parameter %s has unknown shape %s" % (self.name, self._shape))
        init, ctx, default_init = self._deferred_init
        self._ctx = ctx
        self._finish_init(init, default_init)

    def _init_grad(self):
        self._grad = NDArray(nd.zeros(self._shape, dtype=self._data.dtype)._data)
        autograd.mark_variables([self._data], [self._grad], self._grad_req)

    # ----------------------------------------------------------------
    def _check_initialized(self):
        if self._data is not None:
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                "Parameter %s was not initialized because it has unknown shape %s. "
                "Run a forward pass first." % (self.name, self._shape))
        raise RuntimeError(
            "Parameter %s has not been initialized. Call .initialize() first."
            % self.name)

    def data(self, ctx=None):
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad is None:
            raise RuntimeError("Parameter %s has grad_req='null'" % self.name)
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._ctx is None:
            self._check_initialized()
        return self._ctx or []

    def set_data(self, data):
        self.shape = data.shape
        if self._data is None:
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                raise RuntimeError("Parameter %s not initialized" % self.name)
        if not isinstance(data, NDArray):
            data = nd.array(data)
        self._data._data = data.astype(self._data.dtype)._data

    def zero_grad(self):
        if self._grad is not None:
            self._grad._data = nd.zeros(self._grad.shape, dtype=self._grad.dtype)._data

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx = list(ctx)
        if self._data is not None:
            self._data = self._data.as_in_context(ctx[0])
            if self._grad is not None:
                self._init_grad()

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            if self._grad is not None:
                self._init_grad()

    def var(self):
        from ..symbol import Symbol, var
        return var(self.name, shape=self._shape, dtype=self.dtype)

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self._shape, self.dtype)


class Constant(Parameter):
    """Non-trainable constant parameter (ref parameter.py Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _Init(init_mod.Initializer):
            def _init_weight(self, _, arr):
                arr._data = value._data

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_Init())


class ParameterDict:
    """Ordered dict of Parameters with prefix (ref gluon/parameter.py ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __len__(self):
        return len(self._params)

    def __repr__(self):
        s = "%s(" % (self._prefix + " " if self._prefix else "")
        s += "\n  ".join(repr(p) for p in self.values())
        return s + ")"

    def get(self, name, **kwargs):
        """Retrieve or create parameter ``prefix+name`` (ref ParameterDict.get)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    if k == "shape" and v is not None:
                        param.shape = v
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError("No constant named %s" % name)
            param = Constant(name, value)
            self._params[name] = param
        return param

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError("Cannot update because keys overlap: %s" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        elif isinstance(init, str):
            init = init_mod.create(init)
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = block[0]
            if not param.name.startswith(strip_prefix):
                raise ValueError("Prefix %s is to be stripped before saving, but "
                                 "Parameter %s does not start with it" % (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix=""):
        arg_dict = {restore_prefix + k: v for k, v in nd.load(filename).items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise AssertionError("Parameter %s missing in file %s" % (name, filename))
        for name, data in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise AssertionError("Parameter %s in file is not in this dict" % name)
                continue
            param = self._params[name]
            if param._data is None:
                param.shape = data.shape
                if param._deferred_init is not None:
                    param._finish_deferred_init()
                else:
                    param.initialize(ctx=ctx)
            param.set_data(data)
