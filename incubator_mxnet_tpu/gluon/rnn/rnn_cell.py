"""RNN cells (ref python/mxnet/gluon/rnn/rnn_cell.py)."""
from __future__ import annotations

from ... import ndarray as nd
from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "HybridSequentialRNNCell", "ModifierCell",
           "DropoutCell", "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


class RecurrentCell(HybridBlock):
    """Base cell (ref rnn_cell.py RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info.update(kwargs)
            shape = info.pop("shape")
            info.pop("__layout__", None)
            states.append(func(shape, **info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """ref rnn_cell.py unroll — python loop over time (cells are for
        flexibility; the fused rnn_layer scan path is the fast one)."""
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
            batch = seq[0].shape[0]
        else:
            batch = inputs.shape[1 - axis if axis in (0, 1) else 0]
            seq = [s for s in nd.split(inputs, length, axis=axis, squeeze_axis=True)] \
                if length > 1 else [inputs.squeeze(axis)]
        states = begin_state if begin_state is not None else self.begin_state(batch)
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack(*outputs, axis=axis)
            stacked = nd.SequenceMask(stacked, valid_length, True,
                                      axis=axis if axis in (0, 1) else 0)
            outputs = stacked
            merge_outputs = True
        if merge_outputs:
            if not isinstance(outputs, nd.NDArray):
                outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def __call__(self, inputs, states):
        self._counter += 1
        return self.forward(inputs, states)


class RNNCell(RecurrentCell):
    """Elman RNN cell (ref rnn_cell.py RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(hidden_size, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(hidden_size, hidden_size),
                                              init=h2h_weight_initializer,
                                              allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(hidden_size,),
                                            init=i2h_bias_initializer,
                                            allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(hidden_size,),
                                            init=h2h_bias_initializer,
                                            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _ensure_init(self, x):
        if self.i2h_weight._data is None:
            self.i2h_weight.shape = (self._hidden_size, x.shape[-1])
            for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias, self.h2h_bias):
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._ensure_init(inputs)
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                                num_hidden=self._hidden_size, flatten=False)
        h2h = nd.FullyConnected(states[0], self.h2h_weight.data(), self.h2h_bias.data(),
                                num_hidden=self._hidden_size, flatten=False)
        output = nd.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(RecurrentCell):
    """LSTM cell, gate order i,f,g,o like MXNet (ref rnn_cell.py LSTMCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(4 * hidden_size, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(4 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer,
                                              allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(4 * hidden_size,),
                                            init=i2h_bias_initializer,
                                            allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(4 * hidden_size,),
                                            init=h2h_bias_initializer,
                                            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _ensure_init(self, x):
        if self.i2h_weight._data is None:
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])
            for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias, self.h2h_bias):
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._ensure_init(inputs)
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                                num_hidden=4 * self._hidden_size, flatten=False)
        h2h = nd.FullyConnected(states[0], self.h2h_weight.data(), self.h2h_bias.data(),
                                num_hidden=4 * self._hidden_size, flatten=False)
        gates = i2h + h2h
        slice_gates = nd.split(gates, 4, axis=-1)
        in_gate = nd.sigmoid(slice_gates[0])
        forget_gate = nd.sigmoid(slice_gates[1])
        in_transform = nd.tanh(slice_gates[2])
        out_gate = nd.sigmoid(slice_gates[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * nd.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(RecurrentCell):
    """GRU cell, gate order r,z,n like MXNet (ref rnn_cell.py GRUCell)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get("i2h_weight", shape=(3 * hidden_size, input_size),
                                              init=i2h_weight_initializer,
                                              allow_deferred_init=True)
            self.h2h_weight = self.params.get("h2h_weight", shape=(3 * hidden_size, hidden_size),
                                              init=h2h_weight_initializer,
                                              allow_deferred_init=True)
            self.i2h_bias = self.params.get("i2h_bias", shape=(3 * hidden_size,),
                                            init=i2h_bias_initializer,
                                            allow_deferred_init=True)
            self.h2h_bias = self.params.get("h2h_bias", shape=(3 * hidden_size,),
                                            init=h2h_bias_initializer,
                                            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _ensure_init(self, x):
        if self.i2h_weight._data is None:
            self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])
            for p in (self.i2h_weight, self.h2h_weight, self.i2h_bias, self.h2h_bias):
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._ensure_init(inputs)
        prev_h = states[0]
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                                num_hidden=3 * self._hidden_size, flatten=False)
        h2h = nd.FullyConnected(prev_h, self.h2h_weight.data(), self.h2h_bias.data(),
                                num_hidden=3 * self._hidden_size, flatten=False)
        i2h_r, i2h_z, i2h_n = nd.split(i2h, 3, axis=-1)
        h2h_r, h2h_z, h2h_n = nd.split(h2h, 3, axis=-1)
        reset_gate = nd.sigmoid(i2h_r + h2h_r)
        update_gate = nd.sigmoid(i2h_z + h2h_z)
        next_h_tmp = nd.tanh(i2h_n + reset_gate * h2h_n)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack of cells (ref rnn_cell.py SequentialRNNCell)."""

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, func, **kwargs))
        return states

    def forward(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            inputs, s = cell(inputs, states[p: p + n])
            next_states.extend(s)
            p += n
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=None, params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        return self.base_cell.begin_state(batch_size, func, **kwargs)


class DropoutCell(RecurrentCell):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = nd.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def forward(self, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)
        if self.zoneout_outputs > 0.0:
            mask = nd.Dropout(nd.ones_like(next_output), p=self.zoneout_outputs)
            prev = self._prev_output if self._prev_output is not None \
                else nd.zeros_like(next_output)
            next_output = nd.where(mask, next_output, prev)
        if self.zoneout_states > 0.0:
            out_states = []
            for ns, s in zip(next_states, states):
                mask = nd.Dropout(nd.ones_like(ns), p=self.zoneout_states)
                out_states.append(nd.where(mask, ns, s))
            next_states = out_states
        self._prev_output = next_output
        return next_output, next_states


class ResidualCell(ModifierCell):
    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    """ref rnn_cell.py BidirectionalCell."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix=None, params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size) +
                self._children["r_cell"].state_info(batch_size))

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        return (self._children["l_cell"].begin_state(batch_size, func, **kwargs) +
                self._children["r_cell"].begin_state(batch_size, func, **kwargs))

    def __call__(self, inputs, states):
        raise NotImplementedError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        axis = layout.find("T")
        n_l = len(l_cell.state_info())
        states = begin_state if begin_state is not None else self.begin_state(
            inputs.shape[1 - axis if axis in (0, 1) else 0])
        l_out, l_states = l_cell.unroll(length, inputs, states[:n_l], layout, True,
                                        valid_length)
        rev = nd.SequenceReverse(inputs.swapaxes(0, axis) if axis != 0 else inputs,
                                 valid_length, valid_length is not None, axis=0)
        if axis != 0:
            rev = rev.swapaxes(0, axis)
        r_out, r_states = r_cell.unroll(length, rev, states[n_l:], layout, True,
                                        valid_length)
        r_out_rev = nd.SequenceReverse(r_out.swapaxes(0, axis) if axis != 0 else r_out,
                                       valid_length, valid_length is not None, axis=0)
        if axis != 0:
            r_out_rev = r_out_rev.swapaxes(0, axis)
        outputs = nd.concat(l_out, r_out_rev, dim=2)
        return outputs, l_states + r_states


# Hybrid aliases (ref rnn_cell.py HybridRecurrentCell/HybridSequentialRNNCell):
# every cell here is hybridizable — eager and traced paths share one forward —
# so the Hybrid names are the same classes.
HybridRecurrentCell = RecurrentCell
HybridSequentialRNNCell = SequentialRNNCell
