"""gluon.rnn (ref python/mxnet/gluon/rnn/__init__.py)."""
from .rnn_cell import *  # noqa
from .rnn_layer import RNN, LSTM, GRU  # noqa
