"""Fused RNN layers (ref python/mxnet/gluon/rnn/rnn_layer.py + src/operator/rnn-inl.h).

TPU-native design: the monolithic cuDNN RNN op becomes a ``lax.scan`` over the
time axis — gate matmuls batched onto the MXU, the scan compiled by XLA into a
single fused loop (BASELINE config 5). Multi-layer + bidirectional supported.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ... import ndarray as nd
from ...ndarray import NDArray, _apply
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


def _lstm_step(h, c, x_t, wi, wh, bi, bh):
    gates = x_t @ wi.T + h @ wh.T + bi + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _gru_step(h, x_t, wi, wh, bi, bh):
    gi = x_t @ wi.T + bi
    gh = h @ wh.T + bh
    ir, iz, in_ = jnp.split(gi, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(in_ + r * hn)
    return (1 - z) * n + z * h


def _rnn_step(h, x_t, wi, wh, bi, bh, act):
    pre = x_t @ wi.T + h @ wh.T + bi + bh
    return jnp.tanh(pre) if act == "tanh" else jax.nn.relu(pre)


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout, bidirectional,
                 input_size, mode, activation=None,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._activation = activation
        ng = {"rnn": 1, "lstm": 4, "gru": 3}[mode]
        self._gates = ng
        self._i2h, self._h2h, self._i2hb, self._h2hb = [], [], [], []
        with self.name_scope():
            for layer in range(num_layers):
                for d, suffix in zip(range(self._dir), ["l", "r"]):
                    in_sz = input_size if layer == 0 else hidden_size * self._dir
                    shape_known = in_sz > 0
                    args = dict(allow_deferred_init=True)
                    w_i2h = self.params.get("%s%d_i2h_weight" % (suffix, layer),
                                            shape=(ng * hidden_size, in_sz),
                                            init=i2h_weight_initializer, **args)
                    w_h2h = self.params.get("%s%d_h2h_weight" % (suffix, layer),
                                            shape=(ng * hidden_size, hidden_size),
                                            init=h2h_weight_initializer, **args)
                    b_i2h = self.params.get("%s%d_i2h_bias" % (suffix, layer),
                                            shape=(ng * hidden_size,),
                                            init=i2h_bias_initializer, **args)
                    b_h2h = self.params.get("%s%d_h2h_bias" % (suffix, layer),
                                            shape=(ng * hidden_size,),
                                            init=h2h_bias_initializer, **args)
                    self._i2h.append(w_i2h)
                    self._h2h.append(w_h2h)
                    self._i2hb.append(b_i2h)
                    self._h2hb.append(b_h2h)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        states = []
        n_state = 2 if self._mode == "lstm" else 1
        for _ in range(n_state):
            states.append(func((self._num_layers * self._dir, batch_size,
                                self._hidden_size), **kwargs))
        return states if n_state > 1 else states

    def _ensure_init(self, x):
        if self._i2h[0]._data is None:
            in_sz = x.shape[-1]
            for layer in range(self._num_layers):
                for d in range(self._dir):
                    idx = layer * self._dir + d
                    lin = in_sz if layer == 0 else self._hidden_size * self._dir
                    self._i2h[idx].shape = (self._gates * self._hidden_size, lin)
                    for p in (self._i2h[idx], self._h2h[idx], self._i2hb[idx],
                              self._h2hb[idx]):
                        p._finish_deferred_init()

    def forward(self, inputs, states=None):
        self._ensure_init(inputs if self._layout == "TNC"
                          else inputs.swapaxes(0, 1))
        batch = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch)
        if isinstance(states, NDArray):
            states = [states]
        x = inputs if self._layout == "TNC" else inputs.swapaxes(0, 1)

        mode, act = self._mode, self._activation
        num_layers, ndir, hid = self._num_layers, self._dir, self._hidden_size
        has_cell = mode == "lstm"

        def fused(x_d, h0_d, c0_d, *wts):
            # wts: i2h*, h2h*, i2hb*, h2hb* each num_layers*ndir
            L = num_layers * ndir
            wi, wh, bi, bh = wts[:L], wts[L:2 * L], wts[2 * L:3 * L], wts[3 * L:]
            out = x_d
            h_out, c_out = [], []
            for layer in range(num_layers):
                dir_outs = []
                for d in range(ndir):
                    idx = layer * ndir + d
                    seq = out if d == 0 else jnp.flip(out, 0)
                    h0 = h0_d[idx]
                    if has_cell:
                        c0 = c0_d[idx]

                        def step(carry, x_t, _wi=wi[idx], _wh=wh[idx], _bi=bi[idx], _bh=bh[idx]):
                            h, c = carry
                            h2, c2 = _lstm_step(h, c, x_t, _wi, _wh, _bi, _bh)
                            return (h2, c2), h2

                        (hT, cT), ys = lax.scan(step, (h0, c0), seq)
                        c_out.append(cT)
                    elif mode == "gru":
                        def step(h, x_t, _wi=wi[idx], _wh=wh[idx], _bi=bi[idx], _bh=bh[idx]):
                            h2 = _gru_step(h, x_t, _wi, _wh, _bi, _bh)
                            return h2, h2

                        hT, ys = lax.scan(step, h0, seq)
                    else:
                        def step(h, x_t, _wi=wi[idx], _wh=wh[idx], _bi=bi[idx], _bh=bh[idx]):
                            h2 = _rnn_step(h, x_t, _wi, _wh, _bi, _bh, act)
                            return h2, h2

                        hT, ys = lax.scan(step, h0, seq)
                    h_out.append(hT)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    dir_outs.append(ys)
                out = dir_outs[0] if ndir == 1 else jnp.concatenate(dir_outs, axis=-1)
                if self._dropout and layer != num_layers - 1:
                    from ...ndarray import random as _rnd
                    from ... import autograd as _ag
                    if _ag.is_training():
                        keep = 1.0 - self._dropout
                        mask = jax.random.bernoulli(
                            _rnd._next_key(), keep, out.shape).astype(out.dtype)
                        out = out * mask / keep
            hs = jnp.stack(h_out, 0)
            if has_cell:
                return out, hs, jnp.stack(c_out, 0)
            return out, hs

        weights = ([p.data() for p in self._i2h] + [p.data() for p in self._h2h] +
                   [p.data() for p in self._i2hb] + [p.data() for p in self._h2hb])
        if has_cell:
            res = _apply(lambda xd, h0, c0, *w: fused(xd, h0, c0, *w),
                         x, states[0], states[1], *weights)
            out, hT, cT = res
            out_states = [hT, cT]
        else:
            res = _apply(lambda xd, h0, *w: fused(xd, h0, None, *w),
                         x, states[0], *weights)
            out, hT = res
            out_states = [hT]
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        if skip_states:
            return out
        return out, out_states

    def __repr__(self):
        return "%s(%d, num_layers=%d)" % (type(self).__name__, self._hidden_size,
                                          self._num_layers)


class RNN(_RNNLayer):
    """ref rnn_layer.py RNN."""

    def __init__(self, hidden_size, num_layers=1, activation="relu", layout="TNC",
                 dropout=0, bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, "rnn", activation, **kwargs)


class LSTM(_RNNLayer):
    """ref rnn_layer.py LSTM (cuDNN RNN → lax.scan, BASELINE config 5)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    """ref rnn_layer.py GRU."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout, bidirectional,
                         input_size, "gru", **kwargs)
