"""Loss blocks (ref python/mxnet/gluon/loss.py)."""
from __future__ import annotations

import numpy as onp

from .. import ndarray as nd
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss", "SigmoidBCELoss",
           "SoftmaxCrossEntropyLoss", "SoftmaxCELoss", "KLDivLoss", "CTCLoss",
           "HuberLoss", "HingeLoss", "SquaredHingeLoss", "LogisticLoss",
           "TripletLoss", "PoissonNLLLoss", "CosineEmbeddingLoss"]


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(pred, label):
    if pred.shape != label.shape:
        label = label.reshape(pred.shape)
    return label


class Loss(HybridBlock):
    """Base loss (ref loss.py Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "%s(batch_axis=%s, w=%s)" % (
            type(self).__name__, self._batch_axis, self._weight)


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.square(label - pred)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.abs(label - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            if pos_weight is None:
                loss = nd.relu(pred) - pred * label + nd.Activation(
                    -nd.abs(pred), act_type="softrelu")
            else:
                log_weight = 1 + (pos_weight - 1) * label
                loss = pred - pred * label + log_weight * (
                    nd.Activation(-nd.abs(pred), act_type="softrelu") + nd.relu(-pred))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(nd.log(pred + eps) * label + nd.log(1 - pred + eps) * (1 - label))
            else:
                loss = -(nd.log(pred + eps) * label * pos_weight +
                         nd.log(1 - pred + eps) * (1 - label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """ref loss.py SoftmaxCrossEntropyLoss."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False, weight=None,
                 batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -nd.pick(pred, label, axis=self._axis, keepdims=False)
        else:
            label = _reshape_like(pred, label)
            loss = -(pred * label).sum(axis=self._axis, keepdims=False)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(loss.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = nd.log_softmax(pred, axis=self._axis)
        loss = label * (nd.log(label + 1e-12) - pred)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class CTCLoss(Loss):
    """ref loss.py CTCLoss → nn/ctc_loss.cc."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        loss = nd.CTCLoss(pred, label, pred_lengths, label_lengths,
                          use_data_lengths=pred_lengths is not None,
                          use_label_lengths=label_lengths is not None,
                          blank_label="last")
        return _apply_weighting(loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.abs(label - pred)
        loss = nd.where(loss > self._rho,
                        loss - 0.5 * self._rho,
                        (0.5 / self._rho) * nd.square(loss))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.relu(self._margin - pred * label)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = nd.square(nd.relu(self._margin - pred * label))
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = nd.relu(pred) - pred * label + nd.Activation(
            -nd.abs(pred), act_type="softrelu")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        axes = tuple(i for i in range(len(pred.shape)) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(pred, positive)
        negative = _reshape_like(pred, negative)
        loss = (nd.square(positive - pred) - nd.square(negative - pred)).sum(
            axis=tuple(range(1, len(pred.shape))))
        loss = nd.relu(loss + self._margin)
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, target, sample_weight=None, epsilon=1e-08):
        target = _reshape_like(pred, target)
        if self._from_logits:
            loss = nd.exp(pred) - target * pred
        else:
            loss = pred - target * nd.log(pred + epsilon)
        if self._compute_full:
            stirling = target * nd.log(target + 1e-12) - target + 0.5 * nd.log(
                2 * onp.pi * (target + 1e-12))
            stirling = stirling * (target > 1)
            loss = loss + stirling
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(input1, input2)
        cos_sim = (input1 * input2).sum(axis=-1) / (
            input1.norm(axis=-1) * input2.norm(axis=-1) + 1e-12)
        label = label.reshape((-1,))
        loss = nd.where(label == 1, 1.0 - cos_sim,
                        nd.relu(cos_sim - self._margin))
        return _apply_weighting(loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Batchwise Smoothed Deep Metric Learning loss (ref loss.py SDMLLoss,
    Bonadiman et al. 2019): aligned minibatches x1/x2, other rows act as
    in-batch negatives; KL between softmax(-pairwise_dist) and the
    smoothed identity."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self.kl_loss = KLDivLoss(from_logits=True)
        self.smoothing_parameter = smoothing_parameter

    def forward(self, x1, x2):
        n = x1.shape[0]
        diffs = x1.expand_dims(1) - x2.expand_dims(0)       # (N, N, D)
        distances = (diffs ** 2).sum(axis=2)                # (N, N)
        gold = nd.one_hot(nd.arange(n), n)
        labels = gold * (1 - self.smoothing_parameter) \
            + (1.0 - gold) * (self.smoothing_parameter / (n - 1))
        log_probabilities = nd.log_softmax(-distances, axis=1)
        # scale by N like the reference (KLDivLoss averages over the axis)
        return self.kl_loss(log_probabilities, labels) * n


__all__ += ["SDMLLoss"]
