"""gluon.utils (ref python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import math

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """ref utils.py split_data."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along axis %d."
            % (str(data.shape), num_slice, batch_axis))
    if num_slice == 1:
        return [data]
    step = size // num_slice
    slices = [nd.slice_axis(data, batch_axis, i * step,
                            (i + 1) * step if i < num_slice - 1 else size)
              for i in range(num_slice)]
    return slices

def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """ref utils.py split_and_load — slices land on each ctx."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """ref utils.py clip_global_norm."""
    assert len(arrays) > 0
    total_norm = math.sqrt(sum(float((x * x).sum().asscalar()) for x in arrays))
    if check_isfinite and not math.isfinite(total_norm):
        import warnings
        warnings.warn("nan or inf is detected. Clipping results will be undefined.")
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._data = (arr * scale)._data
    return total_norm


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None, retries=5,
             verify_ssl=True):
    raise RuntimeError("network egress is unavailable in this environment; "
                       "place files locally instead (url=%s)" % url)
