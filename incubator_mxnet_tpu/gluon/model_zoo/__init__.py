"""gluon.model_zoo (ref python/mxnet/gluon/model_zoo/__init__.py)."""
from . import vision  # noqa
from .vision import get_model  # noqa
