"""Vision model zoo (ref python/mxnet/gluon/model_zoo/vision/*).

All models are HybridBlocks; hybridize() compiles each into one XLA program.
Pretrained weights are unavailable offline — ``pretrained=True`` raises.
"""
from __future__ import annotations

from .. import nn
from ..block import HybridBlock
from ... import ndarray as nd

__all__ = ["ResNetV1", "ResNetV2", "VGG", "AlexNet", "DenseNet", "SqueezeNet",
           "MobileNet", "MobileNetV2", "Inception3",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1", "resnet152_v1",
           "resnet18_v2", "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
           "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn",
           "vgg19_bn", "alexnet", "densenet121", "densenet161", "densenet169",
           "densenet201", "squeezenet1_0", "squeezenet1_1", "mobilenet1_0",
           "mobilenet0_75", "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
           "inception_v3", "get_model"]


def _no_pretrained(pretrained):
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline; "
                           "load_parameters() from a local file instead")


# ------------------------------------------------------------------ ResNet
class BasicBlockV1(HybridBlock):
    """ref model_zoo/vision/resnet.py BasicBlockV1."""

    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                                in_channels=in_channels))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, 1, stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return nd.Activation(residual + x, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, 1, stride, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1, use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, 1, stride, use_bias=False,
                                          in_channels=in_channels))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.body(x)
        if self.downsample:
            residual = self.downsample(residual)
        return nd.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels, 3, stride, 1, use_bias=False,
                               in_channels=in_channels)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, use_bias=False, in_channels=channels)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.bn1(x)
        x = nd.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = nd.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride, use_bias=False,
                                        in_channels=in_channels)
        else:
            self.downsample = None

    def forward(self, x):
        residual = x
        x = self.bn1(x)
        x = nd.Activation(x, act_type="relu")
        if self.downsample:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = nd.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = nd.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    """ref model_zoo/vision/resnet.py ResNetV1."""

    def __init__(self, block, layers, channels, classes=1000, thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(nn.Conv2D(channels[0], 3, 1, 1, use_bias=False))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1,
                                                   in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index, in_channels=0):
        layer = nn.HybridSequential(prefix="stage%d_" % stage_index)
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels, prefix=""))
        return layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000, thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels) - 1
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(nn.Conv2D(channels[0], 3, 1, 1, use_bias=False))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3, use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(block, num_layer, channels[i + 1],
                                                   stride, i + 1, in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


_resnet_spec = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
_resnet_block_versions = [
    {"basic_block": BasicBlockV1, "bottle_neck": BottleneckV1},
    {"basic_block": BasicBlockV2, "bottle_neck": BottleneckV2},
]


def get_resnet(version, num_layers, pretrained=False, classes=1000, **kwargs):
    _no_pretrained(pretrained)
    block_type, layers, channels = _resnet_spec[num_layers]
    resnet_class = [ResNetV1, ResNetV2][version - 1]
    block_class = _resnet_block_versions[version - 1][block_type]
    return resnet_class(block_class, layers, channels, classes=classes, **kwargs)


def resnet18_v1(**kw): return get_resnet(1, 18, **kw)
def resnet34_v1(**kw): return get_resnet(1, 34, **kw)
def resnet50_v1(**kw): return get_resnet(1, 50, **kw)
def resnet101_v1(**kw): return get_resnet(1, 101, **kw)
def resnet152_v1(**kw): return get_resnet(1, 152, **kw)
def resnet18_v2(**kw): return get_resnet(2, 18, **kw)
def resnet34_v2(**kw): return get_resnet(2, 34, **kw)
def resnet50_v2(**kw): return get_resnet(2, 50, **kw)
def resnet101_v2(**kw): return get_resnet(2, 101, **kw)
def resnet152_v2(**kw): return get_resnet(2, 152, **kw)


# ------------------------------------------------------------------ VGG
class VGG(HybridBlock):
    """ref model_zoo/vision/vgg.py."""

    def __init__(self, layers, filters, classes=1000, batch_norm=False, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(filters)
        with self.name_scope():
            self.features = self._make_features(layers, filters, batch_norm)
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(rate=0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(rate=0.5))
            self.output = nn.Dense(classes)

    def _make_features(self, layers, filters, batch_norm):
        featurizer = nn.HybridSequential(prefix="")
        for i, num in enumerate(layers):
            for _ in range(num):
                featurizer.add(nn.Conv2D(filters[i], kernel_size=3, padding=1))
                if batch_norm:
                    featurizer.add(nn.BatchNorm())
                featurizer.add(nn.Activation("relu"))
            featurizer.add(nn.MaxPool2D(strides=2))
        return featurizer

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


_vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
             13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
             16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
             19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


def get_vgg(num_layers, pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    layers, filters = _vgg_spec[num_layers]
    return VGG(layers, filters, **kwargs)


def vgg11(**kw): return get_vgg(11, **kw)
def vgg13(**kw): return get_vgg(13, **kw)
def vgg16(**kw): return get_vgg(16, **kw)
def vgg19(**kw): return get_vgg(19, **kw)
def vgg11_bn(**kw): return get_vgg(11, batch_norm=True, **kw)
def vgg13_bn(**kw): return get_vgg(13, batch_norm=True, **kw)
def vgg16_bn(**kw): return get_vgg(16, batch_norm=True, **kw)
def vgg19_bn(**kw): return get_vgg(19, batch_norm=True, **kw)


# ------------------------------------------------------------------ AlexNet
class AlexNet(HybridBlock):
    """ref model_zoo/vision/alexnet.py."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(64, 11, 4, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(192, 5, padding=2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(384, 3, padding=1, activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


def alexnet(pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    return AlexNet(**kwargs)


# ------------------------------------------------------------------ DenseNet
class _DenseBlock(HybridBlock):
    def __init__(self, num_layers, bn_size, growth_rate, dropout, **kwargs):
        super().__init__(**kwargs)
        self.blocks = []
        for i in range(num_layers):
            blk = nn.HybridSequential(prefix="")
            blk.add(nn.BatchNorm())
            blk.add(nn.Activation("relu"))
            blk.add(nn.Conv2D(bn_size * growth_rate, kernel_size=1, use_bias=False))
            blk.add(nn.BatchNorm())
            blk.add(nn.Activation("relu"))
            blk.add(nn.Conv2D(growth_rate, kernel_size=3, padding=1, use_bias=False))
            if dropout:
                blk.add(nn.Dropout(dropout))
            self.register_child(blk, "b%d" % i)
            self.blocks.append(blk)

    def forward(self, x):
        for blk in self.blocks:
            out = blk(x)
            x = nd.concat(x, out, dim=1)
        return x


def _make_transition(num_output_features):
    out = nn.HybridSequential(prefix="")
    out.add(nn.BatchNorm())
    out.add(nn.Activation("relu"))
    out.add(nn.Conv2D(num_output_features, kernel_size=1, use_bias=False))
    out.add(nn.AvgPool2D(pool_size=2, strides=2))
    return out


class DenseNet(HybridBlock):
    """ref model_zoo/vision/densenet.py."""

    def __init__(self, num_init_features, growth_rate, block_config, bn_size=4,
                 dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(num_init_features, kernel_size=7, strides=2,
                                        padding=3, use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2, padding=1))
            num_features = num_init_features
            for i, num_layers in enumerate(block_config):
                self.features.add(_DenseBlock(num_layers, bn_size, growth_rate, dropout))
                num_features = num_features + num_layers * growth_rate
                if i != len(block_config) - 1:
                    num_features = num_features // 2
                    self.features.add(_make_transition(num_features))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.AvgPool2D(pool_size=7))
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


_densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                  161: (96, 48, [6, 12, 36, 24]),
                  169: (64, 32, [6, 12, 32, 32]),
                  201: (64, 32, [6, 12, 48, 32])}


def get_densenet(num_layers, pretrained=False, **kwargs):
    _no_pretrained(pretrained)
    num_init_features, growth_rate, block_config = _densenet_spec[num_layers]
    return DenseNet(num_init_features, growth_rate, block_config, **kwargs)


def densenet121(**kw): return get_densenet(121, **kw)
def densenet161(**kw): return get_densenet(161, **kw)
def densenet169(**kw): return get_densenet(169, **kw)
def densenet201(**kw): return get_densenet(201, **kw)


# ------------------------------------------------------------------ SqueezeNet
class _Fire(HybridBlock):
    def __init__(self, squeeze_channels, expand1x1_channels, expand3x3_channels, **kw):
        super().__init__(**kw)
        self.squeeze = nn.Conv2D(squeeze_channels, kernel_size=1, activation="relu")
        self.expand1x1 = nn.Conv2D(expand1x1_channels, kernel_size=1, activation="relu")
        self.expand3x3 = nn.Conv2D(expand3x3_channels, kernel_size=3, padding=1,
                                   activation="relu")

    def forward(self, x):
        x = self.squeeze(x)
        return nd.concat(self.expand1x1(x), self.expand3x3(x), dim=1)


class SqueezeNet(HybridBlock):
    """ref model_zoo/vision/squeezenet.py."""

    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        assert version in ("1.0", "1.1")
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if version == "1.0":
                self.features.add(nn.Conv2D(96, kernel_size=7, strides=2, activation="relu"))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(64, 256, 256))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True))
                self.features.add(_Fire(64, 256, 256))
            else:
                self.features.add(nn.Conv2D(64, kernel_size=3, strides=2, activation="relu"))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(_Fire(16, 64, 64))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(_Fire(32, 128, 128))
                self.features.add(nn.MaxPool2D(pool_size=3, strides=2, ceil_mode=True))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(48, 192, 192))
                self.features.add(_Fire(64, 256, 256))
                self.features.add(_Fire(64, 256, 256))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, kernel_size=1, activation="relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


def squeezenet1_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return SqueezeNet("1.1", **kw)


# ------------------------------------------------------------------ MobileNet
def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1, active=True,
              relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group, use_bias=False))
    out.add(nn.BatchNorm(scale=True))
    if active:
        out.add(nn.HybridLambda(lambda x: nd.clip(x, 0, 6) if relu6 else nd.relu(x)))


class MobileNet(HybridBlock):
    """ref model_zoo/vision/mobilenet.py (v1, depthwise-separable convs)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _add_conv(self.features, int(32 * multiplier), 3, 2, 1)
            dw_channels = [int(x * multiplier) for x in
                           [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
            channels = [int(x * multiplier) for x in
                        [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
            strides = [1, 2, 1, 2, 1, 2] + [1] * 5 + [2, 1]
            for dwc, c, s in zip(dw_channels, channels, strides):
                _add_conv(self.features, dwc, 3, s, 1, num_group=dwc)  # depthwise
                _add_conv(self.features, c, 1, 1, 0)                   # pointwise
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


class _LinearBottleneck(HybridBlock):
    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        self.out = nn.HybridSequential()
        _add_conv(self.out, in_channels * t, relu6=True)
        _add_conv(self.out, in_channels * t, 3, stride, 1, num_group=in_channels * t,
                  relu6=True)
        _add_conv(self.out, channels, active=False)

    def forward(self, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNetV2(HybridBlock):
    """ref model_zoo/vision/mobilenet.py MobileNetV2."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="features_")
            _add_conv(self.features, int(32 * multiplier), 3, 2, 1, relu6=True)
            in_channels_group = [int(x * multiplier) for x in
                                 [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4 +
                                 [96] * 3 + [160] * 3]
            channels_group = [int(x * multiplier) for x in
                              [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3 +
                              [160] * 3 + [320]]
            ts = [1] + [6] * 16
            strides = [1, 2, 1, 2, 1, 1, 2, 1, 1, 1, 1, 1, 1, 2, 1, 1, 1]
            for in_c, c, t, s in zip(in_channels_group, channels_group, ts, strides):
                self.features.add(_LinearBottleneck(in_c, c, t, s))
            last_channels = int(1280 * multiplier) if multiplier > 1.0 else 1280
            _add_conv(self.features, last_channels, relu6=True)
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix="output_")
            self.output.add(nn.Conv2D(classes, 1, use_bias=False, prefix="pred_"))
            self.output.add(nn.Flatten())

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


def mobilenet1_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return MobileNet(1.0, **kw)


def mobilenet0_75(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return MobileNet(0.75, **kw)


def mobilenet0_5(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return MobileNet(0.5, **kw)


def mobilenet0_25(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return MobileNet(0.25, **kw)


def mobilenet_v2_1_0(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return MobileNetV2(1.0, **kw)


# ------------------------------------------------------------------ Inception v3
def _make_basic_conv(**kwargs):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential(prefix="")
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    setting_names = ["channels", "kernel_size", "strides", "padding"]
    for setting in conv_settings:
        kwargs = {}
        for i, value in enumerate(setting):
            if value is not None:
                kwargs[setting_names[i]] = value
        out.add(_make_basic_conv(**kwargs))
    return out


class _Concurrent(HybridBlock):
    """Run children on same input, concat on channel axis."""

    def add(self, block):
        self.register_child(block)

    def forward(self, x):
        return nd.concat(*[blk(x) for blk in self._children.values()], dim=1)


def _make_A(pool_features, prefix):
    out = _Concurrent(prefix=prefix)
    out.add(_make_branch(None, (64, 1, None, None)))
    out.add(_make_branch(None, (48, 1, None, None), (64, 5, None, 2)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1), (96, 3, None, 1)))
    out.add(_make_branch("avg", (pool_features, 1, None, None)))
    return out


def _make_B(prefix):
    out = _Concurrent(prefix=prefix)
    out.add(_make_branch(None, (384, 3, 2, None)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1), (96, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


def _make_C(channels_7x7, prefix):
    out = _Concurrent(prefix=prefix)
    out.add(_make_branch(None, (192, 1, None, None)))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0))))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (192, (1, 7), None, (0, 3))))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


def _make_D(prefix):
    out = _Concurrent(prefix=prefix)
    out.add(_make_branch(None, (192, 1, None, None), (320, 3, 2, None)))
    out.add(_make_branch(None, (192, 1, None, None), (192, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0)), (192, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


class _SplitConcat(HybridBlock):
    def __init__(self, first, b1, b2, **kw):
        super().__init__(**kw)
        self.first = first
        self.b1 = b1
        self.b2 = b2

    def forward(self, x):
        x = self.first(x)
        return nd.concat(self.b1(x), self.b2(x), dim=1)


def _make_E(prefix):
    out = _Concurrent(prefix=prefix)
    out.add(_make_branch(None, (320, 1, None, None)))
    out.add(_SplitConcat(_make_basic_conv(channels=384, kernel_size=1),
                         _make_basic_conv(channels=384, kernel_size=(1, 3), padding=(0, 1)),
                         _make_basic_conv(channels=384, kernel_size=(3, 1), padding=(1, 0))))
    out.add(_SplitConcat(
        _seq(_make_basic_conv(channels=448, kernel_size=1),
             _make_basic_conv(channels=384, kernel_size=3, padding=1)),
        _make_basic_conv(channels=384, kernel_size=(1, 3), padding=(0, 1)),
        _make_basic_conv(channels=384, kernel_size=(3, 1), padding=(1, 0))))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


def _seq(*blocks):
    s = nn.HybridSequential(prefix="")
    for b in blocks:
        s.add(b)
    return s


class Inception3(HybridBlock):
    """ref model_zoo/vision/inception.py."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(_make_basic_conv(channels=32, kernel_size=3, strides=2))
            self.features.add(_make_basic_conv(channels=32, kernel_size=3))
            self.features.add(_make_basic_conv(channels=64, kernel_size=3, padding=1))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_basic_conv(channels=80, kernel_size=1))
            self.features.add(_make_basic_conv(channels=192, kernel_size=3))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
            self.features.add(_make_A(32, "A1_"))
            self.features.add(_make_A(64, "A2_"))
            self.features.add(_make_A(64, "A3_"))
            self.features.add(_make_B("B_"))
            self.features.add(_make_C(128, "C1_"))
            self.features.add(_make_C(160, "C2_"))
            self.features.add(_make_C(160, "C3_"))
            self.features.add(_make_C(192, "C4_"))
            self.features.add(_make_D("D_"))
            self.features.add(_make_E("E1_"))
            self.features.add(_make_E("E2_"))
            self.features.add(nn.AvgPool2D(pool_size=8))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        return self.output(x)


def inception_v3(pretrained=False, **kw):
    _no_pretrained(pretrained)
    return Inception3(**kw)


_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1, "resnet50_v1": resnet50_v1,
    "resnet101_v1": resnet101_v1, "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn, "alexnet": alexnet,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    """ref model_zoo/vision/__init__.py get_model."""
    name = name.lower()
    if name not in _models:
        raise ValueError("Model %s not supported. Available: %s"
                         % (name, sorted(_models)))
    return _models[name](**kwargs)
