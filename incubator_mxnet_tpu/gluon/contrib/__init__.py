"""gluon.contrib (ref python/mxnet/gluon/contrib/) — estimator et al."""
from . import estimator  # noqa
from . import nn  # noqa
