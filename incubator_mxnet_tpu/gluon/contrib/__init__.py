"""gluon.contrib (ref python/mxnet/gluon/contrib/)."""
from . import estimator  # noqa
from . import nn  # noqa
from . import cnn  # noqa
from . import data  # noqa
from . import rnn  # noqa
