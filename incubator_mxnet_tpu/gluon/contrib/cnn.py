"""gluon.contrib.cnn (ref python/mxnet/gluon/contrib/cnn/conv_layers.py:
DeformableConvolution, ModulatedDeformableConvolution).

The offset (and DCNv2 mask) branch is an ordinary convolution initialized
to zeros, exactly like the reference; the deformable sampling itself is
the einsum/gather lowering in ops/deformable.py.
"""
from __future__ import annotations

from ... import ndarray as nd
from ...ndarray.ndarray import _apply
from ...ops.deformable import deformable_conv2d
from ..block import HybridBlock

__all__ = ["DeformableConvolution", "ModulatedDeformableConvolution"]


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


class DeformableConvolution(HybridBlock):
    """Deformable conv v1 (ref conv_layers.py DeformableConvolution).

    Two branches: `offset = Conv(x)` (zero-init so training starts as a
    plain conv) and the deformable conv consuming (x, offset).
    """

    _use_mask = False

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros", offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", num_deformable_group=1,
                 **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._kernel = _pair(kernel_size)
        self._strides = _pair(strides)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        self._ndg = num_deformable_group
        self._activation = activation
        K = self._kernel[0] * self._kernel[1]
        off_ch = self._ndg * (3 if self._use_mask else 2) * K
        with self.name_scope():
            from ..nn import Conv2D
            self._offset = Conv2D(off_ch, self._kernel, self._strides,
                                  self._padding, self._dilation,
                                  in_channels=in_channels,
                                  weight_initializer=offset_weight_initializer,
                                  bias_initializer=offset_bias_initializer,
                                  prefix="offset_")
            self.weight = self.params.get(
                "weight", shape=(channels, in_channels) + self._kernel,
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(channels,), init=bias_initializer) \
                if use_bias else None

    def _ensure_init(self, x):
        if self.weight._data is None:
            self.weight.shape = (self._channels, x.shape[1]) + self._kernel
            self.weight._finish_deferred_init()

    def forward(self, x):
        self._ensure_init(x)
        K = self._kernel[0] * self._kernel[1]
        raw = self._offset(x)
        use_mask, use_bias = self._use_mask, self.bias is not None
        if use_mask:
            off = raw.slice_axis(axis=1, begin=0, end=self._ndg * 2 * K)
            m = nd.sigmoid(
                raw.slice_axis(axis=1, begin=self._ndg * 2 * K, end=None))
            args = [x, off, m, self.weight.data()]
        else:
            args = [x, raw, self.weight.data()]
        if use_bias:
            args.append(self.bias.data())

        def fn(*ds):
            i = 2
            mm = ds[i] if use_mask else None
            i += use_mask
            ww = ds[i]
            bb = ds[i + 1] if use_bias else None
            return deformable_conv2d(
                ds[0], ds[1], ww, bias=bb, kernel=self._kernel,
                stride=self._strides, pad=self._padding,
                dilate=self._dilation, num_deformable_group=self._ndg,
                mask=mm)

        out = _apply(fn, *args)
        if self._activation:
            out = nd.Activation(out, act_type=self._activation)
        return out


class ModulatedDeformableConvolution(DeformableConvolution):
    """DCNv2 (ref conv_layers.py ModulatedDeformableConvolution): adds a
    sigmoid modulation mask per sampling tap."""

    _use_mask = True
