"""Gluon Estimator — high-level fit loop with event handlers
(ref python/mxnet/gluon/contrib/estimator/estimator.py + event_handler.py)."""
from __future__ import annotations

import logging
import time

from ... import autograd, metric as metric_mod
from ...ndarray import NDArray

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch == self.max_batch:
            self.stop_training = True
        return self.stop_training

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch == self.max_epoch:
            self.stop_training = True
        return self.stop_training


class MetricHandler(EpochBegin, BatchEnd):
    def __init__(self, train_metrics):
        self.train_metrics = train_metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.train_metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        for m in self.train_metrics:
            if isinstance(m, metric_mod.Loss):
                m.update(0, loss)
            else:
                m.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin, BatchEnd):
    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0

    def train_begin(self, estimator, *args, **kwargs):
        # perf_counter anchors: train/epoch cost are durations — an NTP
        # clock step mid-run must not corrupt them (R006)
        self.train_start = time.perf_counter()
        logging.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        logging.info("Train finished using total %ds",
                     time.perf_counter() - self.train_start)
        for m in self.metrics:
            name, value = m.get()
            logging.info("Train end: %s: %.4f", name, value)

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int):
            self.batch_index += 1
            if self.batch_index % self.log_interval == 0:
                msg = "[Epoch %d][Batch %d]" % (self.current_epoch, self.batch_index)
                for m in self.metrics:
                    name, value = m.get()
                    msg += " %s: %.4f" % (name, value)
                logging.info(msg)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.perf_counter()

    def epoch_end(self, estimator, *args, **kwargs):
        msg = "[Epoch %d] finished in %.3fs:" % (
            self.current_epoch, time.perf_counter() - self.epoch_start)
        for m in self.metrics:
            name, value = m.get()
            msg += " %s: %.4f" % (name, value)
        logging.info(msg)
        self.current_epoch += 1
        self.batch_index = 0


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    def __init__(self, model_dir, model_prefix="model", monitor=None, verbose=0,
                 save_best=False, mode="auto", epoch_period=1, batch_period=None,
                 max_checkpoints=5, resume_from_checkpoint=False):
        import os
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.epoch_period = epoch_period
        self.current_epoch = 0
        os.makedirs(model_dir, exist_ok=True)

    def epoch_end(self, estimator, *args, **kwargs):
        import os
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            path = os.path.join(self.model_dir, "%s-epoch%d.params"
                                % (self.model_prefix, self.current_epoch))
            estimator.net.save_parameters(path)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    def __init__(self, monitor, min_delta=0, patience=0, mode="auto", baseline=None):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.wait = 0
        self.best = None
        self.stop_training = False

    def epoch_end(self, estimator, *args, **kwargs):
        _, current = self.monitor.get()
        if self.best is None or current < self.best - self.min_delta:
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
        return self.stop_training


class Estimator:
    """ref estimator.py Estimator."""

    def __init__(self, net, loss, train_metrics=None, trainer=None, context=None):
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics if isinstance(train_metrics, list) else (
            [train_metrics] if train_metrics else [metric_mod.Accuracy()])
        self.trainer = trainer
        self.train_loss_metric = metric_mod.Loss("train_loss")

    def evaluate(self, val_data, val_metrics=None):
        val_metrics = val_metrics or self.train_metrics
        for m in val_metrics:
            m.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            pred = self.net(data)
            for m in val_metrics:
                m.update([label], [pred])
        return val_metrics

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None):
        handlers = list(event_handlers or [])
        stop_handler = StoppingHandler(epochs, batches)
        handlers.append(stop_handler)
        handlers.append(MetricHandler(self.train_metrics))
        for h in handlers:
            if isinstance(h, TrainBegin):
                h.train_begin(self)
        while not stop_handler.stop_training:
            for h in handlers:
                if isinstance(h, EpochBegin):
                    h.epoch_begin(self)
            for batch in train_data:
                data, label = batch[0], batch[1]
                batch_size = data.shape[0]
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                self.trainer.step(batch_size)
                self.train_loss_metric.update(0, [loss])
                stop = False
                for h in handlers:
                    if isinstance(h, BatchEnd):
                        if h.batch_end(self, pred=[pred], label=[label], loss=[loss]):
                            stop = True
                if stop or stop_handler.stop_training:
                    break
            for h in handlers:
                if isinstance(h, EpochEnd):
                    h.epoch_end(self)
        for h in handlers:
            if isinstance(h, TrainEnd):
                h.train_end(self)
