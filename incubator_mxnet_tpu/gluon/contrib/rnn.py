"""Contrib RNN cells (ref python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py
and rnn_cell.py: Conv{1,2,3}D{RNN,LSTM,GRU}Cell, VariationalDropoutCell,
LSTMPCell).

TPU-native: the conv cells are ordinary convolutions feeding the same gate
math as the dense cells — XLA fuses gate elementwise chains into the conv
epilogue; cells compose with the fused `lax.scan` unroll in rnn_layer the
same way the dense cells do.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..rnn.rnn_cell import RecurrentCell, ModifierCell, LSTMCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
           "VariationalDropoutCell", "LSTMPCell"]


def _tup(v, n):
    return tuple(v) if isinstance(v, (tuple, list)) else (v,) * n


class _ConvRNNCellBase(RecurrentCell):
    """Shared machinery of the conv cells (ref conv_rnn_cell.py _BaseConvRNNCell).

    input_shape: (C, *spatial) without the batch axis — state shape must be
    known up front (it feeds begin_state), unlike dense cells' deferred
    input_size.
    """

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1, activation="tanh",
                 dims=2, num_gates=1, **kwargs):
        super().__init__(**kwargs)
        self._dims = dims
        self._input_shape = tuple(input_shape)
        self._hc = hidden_channels
        self._activation = activation
        self._num_gates = num_gates
        self._i2h_kernel = _tup(i2h_kernel, dims)
        self._h2h_kernel = _tup(h2h_kernel, dims)
        for k in self._h2h_kernel:
            if k % 2 != 1:
                raise ValueError(
                    "h2h_kernel must be odd so the state keeps its spatial "
                    "shape; got %s" % (self._h2h_kernel,))
        self._i2h_pad = _tup(i2h_pad, dims)
        self._i2h_dilate = _tup(i2h_dilate, dims)
        self._h2h_dilate = _tup(h2h_dilate, dims)
        self._h2h_pad = tuple(d * (k - 1) // 2
                              for d, k in zip(self._h2h_dilate, self._h2h_kernel))
        in_c, spatial = self._input_shape[0], self._input_shape[1:]
        self._state_spatial = tuple(
            (s + 2 * p - d * (k - 1) - 1) + 1
            for s, p, d, k in zip(spatial, self._i2h_pad, self._i2h_dilate,
                                  self._i2h_kernel))
        g = num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(g * hidden_channels, in_c) + self._i2h_kernel)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(g * hidden_channels, hidden_channels) + self._h2h_kernel)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(g * hidden_channels,), init="zeros")
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(g * hidden_channels,), init="zeros")

    def state_info(self, batch_size=0):
        shape = (batch_size, self._hc) + self._state_spatial
        n = 2 if isinstance(self, _ConvLSTMMixin) else 1
        return [{"shape": shape, "__layout__": "NC" + "DHW"[3 - self._dims:]}
                for _ in range(n)]

    def _conv_gates(self, inputs, state):
        ones = (1,) * self._dims
        i2h = nd.Convolution(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                             kernel=self._i2h_kernel, stride=ones,
                             dilate=self._i2h_dilate, pad=self._i2h_pad,
                             num_filter=self._num_gates * self._hc)
        h2h = nd.Convolution(state, self.h2h_weight.data(), self.h2h_bias.data(),
                             kernel=self._h2h_kernel, stride=ones,
                             dilate=self._h2h_dilate, pad=self._h2h_pad,
                             num_filter=self._num_gates * self._hc)
        return i2h, h2h


class _ConvRNNMixin:
    def _alias(self):
        return "conv_rnn"

    def forward(self, inputs, states):
        i2h, h2h = self._conv_gates(inputs, states[0])
        out = nd.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class _ConvLSTMMixin:
    def _alias(self):
        return "conv_lstm"

    def forward(self, inputs, states):
        i2h, h2h = self._conv_gates(inputs, states[0])
        gates = i2h + h2h
        i, f, g, o = nd.split(gates, 4, axis=1)
        in_gate = nd.sigmoid(i)
        forget = nd.sigmoid(f)
        transform = nd.Activation(g, act_type=self._activation)
        out_gate = nd.sigmoid(o)
        next_c = forget * states[1] + in_gate * transform
        next_h = out_gate * nd.Activation(next_c, act_type=self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUMixin:
    def _alias(self):
        return "conv_gru"

    def forward(self, inputs, states):
        i2h, h2h = self._conv_gates(inputs, states[0])
        i_r, i_z, i_n = nd.split(i2h, 3, axis=1)
        h_r, h_z, h_n = nd.split(h2h, 3, axis=1)
        reset = nd.sigmoid(i_r + h_r)
        update = nd.sigmoid(i_z + h_z)
        newmem = nd.Activation(i_n + reset * h_n, act_type=self._activation)
        out = (1.0 - update) * newmem + update * states[0]
        return out, [out]


def _make_cell(name, mixin, dims, gates):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, i2h_dilate=1, h2h_dilate=1, activation="tanh",
                 **kwargs):
        _ConvRNNCellBase.__init__(
            self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
            i2h_pad=i2h_pad, i2h_dilate=i2h_dilate, h2h_dilate=h2h_dilate,
            activation=activation, dims=dims, num_gates=gates, **kwargs)
    cls = type(name, (mixin, _ConvRNNCellBase), {"__init__": __init__})
    cls.__doc__ = ("%s (ref conv_rnn_cell.py %s): convolutional recurrence "
                   "over %dD feature maps." % (name, name, dims))
    return cls


Conv1DRNNCell = _make_cell("Conv1DRNNCell", _ConvRNNMixin, 1, 1)
Conv2DRNNCell = _make_cell("Conv2DRNNCell", _ConvRNNMixin, 2, 1)
Conv3DRNNCell = _make_cell("Conv3DRNNCell", _ConvRNNMixin, 3, 1)
Conv1DLSTMCell = _make_cell("Conv1DLSTMCell", _ConvLSTMMixin, 1, 4)
Conv2DLSTMCell = _make_cell("Conv2DLSTMCell", _ConvLSTMMixin, 2, 4)
Conv3DLSTMCell = _make_cell("Conv3DLSTMCell", _ConvLSTMMixin, 3, 4)
Conv1DGRUCell = _make_cell("Conv1DGRUCell", _ConvGRUMixin, 1, 3)
Conv2DGRUCell = _make_cell("Conv2DGRUCell", _ConvGRUMixin, 2, 3)
Conv3DGRUCell = _make_cell("Conv3DGRUCell", _ConvGRUMixin, 3, 3)


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask reused across all time steps (ref rnn_cell.py
    VariationalDropoutCell, Gal & Ghahramani)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._drop_inputs = drop_inputs
        self._drop_states = drop_states
        self._drop_outputs = drop_outputs
        self.reset()

    def reset(self):
        super().reset()
        self._mask_in = None
        self._mask_state = None
        self._mask_out = None

    def _mask(self, cache_name, x, rate):
        mask = getattr(self, cache_name)
        if mask is None:
            keep = 1.0 - rate
            mask = nd.random.uniform(shape=x.shape) < keep
            mask = mask.astype(x.dtype) / keep
            setattr(self, cache_name, mask)
        return x * mask

    def forward(self, inputs, states):
        from ... import autograd
        if autograd.is_training():
            if self._drop_inputs:
                inputs = self._mask("_mask_in", inputs, self._drop_inputs)
            if self._drop_states:
                states = [self._mask("_mask_state", states[0], self._drop_states)] \
                    + list(states[1:])
        out, nstates = self.base_cell(inputs, states)
        if autograd.is_training() and self._drop_outputs:
            out = self._mask("_mask_out", out, self._drop_outputs)
        return out, nstates


class LSTMPCell(RecurrentCell):
    """LSTM with a hidden-state projection (ref rnn_cell.py LSTMPCell,
    Sak et al. 2014). States: [r (projected), c]."""

    def __init__(self, hidden_size, projection_size, input_size=0, **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,), init="zeros")
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,), init="zeros")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def _ensure_init(self, x):
        if self.i2h_weight._data is None:
            self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])
            for p in (self.i2h_weight, self.h2h_weight, self.h2r_weight,
                      self.i2h_bias, self.h2h_bias):
                p._finish_deferred_init()

    def forward(self, inputs, states):
        self._ensure_init(inputs)
        i2h = nd.FullyConnected(inputs, self.i2h_weight.data(), self.i2h_bias.data(),
                                num_hidden=4 * self._hidden_size, flatten=False)
        h2h = nd.FullyConnected(states[0], self.h2h_weight.data(), self.h2h_bias.data(),
                                num_hidden=4 * self._hidden_size, flatten=False)
        gates = i2h + h2h
        i, f, g, o = nd.split(gates, 4, axis=-1)
        next_c = nd.sigmoid(f) * states[1] + nd.sigmoid(i) * nd.tanh(g)
        next_h = nd.sigmoid(o) * nd.tanh(next_c)
        next_r = nd.FullyConnected(next_h, self.h2r_weight.data(), None,
                                   num_hidden=self._projection_size,
                                   no_bias=True, flatten=False)
        return next_r, [next_r, next_c]
