"""gluon.contrib.nn (ref python/mxnet/gluon/contrib/nn/basic_layers.py).

SyncBatchNorm lives here for reference API parity; on TPU it is plain
BatchNorm (SPMD batch stats are already global — see the class docstring).
"""
from ..nn import SyncBatchNorm, HybridSequential  # noqa

__all__ = ["SyncBatchNorm", "Concurrent", "HybridConcurrent", "Identity"]


class HybridConcurrent(HybridSequential):
    """Run children on the same input and concat outputs
    (ref contrib/nn/basic_layers.py HybridConcurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd
        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


Concurrent = HybridConcurrent


class Identity(HybridSequential):
    """ref contrib/nn/basic_layers.py Identity."""

    def forward(self, x):
        return x


class SparseEmbedding(HybridSequential):
    """ref contrib/nn/basic_layers.py SparseEmbedding: embedding whose
    gradient is row_sparse. TPU-native: delegates to nn.Embedding with
    sparse_grad=True — the compiled step keeps the gather VJP as a scatter
    (never materializing the dense gradient inside the program), which is
    the XLA equivalent of the reference's row_sparse grad."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ..nn import Embedding
        with self.name_scope():
            self.add(Embedding(input_dim, output_dim, dtype=dtype,
                               weight_initializer=weight_initializer,
                               sparse_grad=True))


class _PixelShuffle(HybridSequential):
    """Base pixel shuffle (ref contrib/nn/basic_layers.py PixelShuffle*D;
    Shi et al. 2016): rearrange channels into upscaled spatial dims via
    reshape+transpose — pure layout ops, free under XLA fusion."""

    def __init__(self, factor, dims, **kwargs):
        super().__init__(**kwargs)
        self._dims = dims
        f = factor if isinstance(factor, (tuple, list)) else (factor,) * dims
        self._factor = tuple(int(v) for v in f)

    def forward(self, x):
        import jax.numpy as jnp
        from ...ndarray.ndarray import _apply
        fs = self._factor
        d = self._dims

        def fn(a):
            N = a.shape[0]
            C = a.shape[1]
            spatial = a.shape[2:]
            prod = 1
            for v in fs:
                prod *= v
            c_out = C // prod
            # (N, c_out, f1..fd, s1..sd) → interleave fi after si
            a = a.reshape((N, c_out) + fs + spatial)
            perm = [0, 1]
            for i in range(d):
                perm += [2 + d + i, 2 + i]
            a = a.transpose(perm)
            out_spatial = tuple(s * f for s, f in zip(spatial, fs))
            return a.reshape((N, c_out) + out_spatial)

        return _apply(fn, x)


class PixelShuffle1D(_PixelShuffle):
    def __init__(self, factor, **kw):
        super().__init__(factor, 1, **kw)


class PixelShuffle2D(_PixelShuffle):
    def __init__(self, factor, **kw):
        super().__init__(factor, 2, **kw)


class PixelShuffle3D(_PixelShuffle):
    def __init__(self, factor, **kw):
        super().__init__(factor, 3, **kw)


__all__ += ["SparseEmbedding", "PixelShuffle1D", "PixelShuffle2D",
            "PixelShuffle3D"]
