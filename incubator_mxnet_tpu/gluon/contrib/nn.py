"""gluon.contrib.nn (ref python/mxnet/gluon/contrib/nn/basic_layers.py).

SyncBatchNorm lives here for reference API parity; on TPU it is plain
BatchNorm (SPMD batch stats are already global — see the class docstring).
"""
from ..nn import SyncBatchNorm, HybridSequential  # noqa

__all__ = ["SyncBatchNorm", "Concurrent", "HybridConcurrent", "Identity"]


class HybridConcurrent(HybridSequential):
    """Run children on the same input and concat outputs
    (ref contrib/nn/basic_layers.py HybridConcurrent)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        from ... import ndarray as nd
        outs = [child(x) for child in self._children.values()]
        return nd.concat(*outs, dim=self.axis)


Concurrent = HybridConcurrent


class Identity(HybridSequential):
    """ref contrib/nn/basic_layers.py Identity."""

    def forward(self, x):
        return x
