"""gluon.contrib.data (ref python/mxnet/gluon/contrib/data/: sampler.py
IntervalSampler, text.py WikiText2/WikiText103).

Text datasets honor the reference's on-disk layout (one token stream per
split file); in this zero-egress build they synthesize a deterministic
Zipf-distributed corpus when the files are absent, matching the synthetic
fallback the vision datasets use (gluon/data/vision.py _synthetic).
"""
from __future__ import annotations

import os

import numpy as onp

from ..data.sampler import Sampler
from ..data.dataset import Dataset

__all__ = ["IntervalSampler", "WikiText2", "WikiText103"]


class IntervalSampler(Sampler):
    """[0, length) visited at stride `interval`, rolling over to each skipped
    start (ref contrib/data/sampler.py IntervalSampler)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        starts = range(self._interval if self._rollover else 1)
        for start in starts:
            yield from range(start, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))


class _WikiText(Dataset):
    """Token-id sequence dataset cut into fixed-length segments
    (ref contrib/data/text.py _WikiText): each item is (seq, label) with
    label the next-token shift, ready for LM training."""

    _vocab_size = 2048

    def __init__(self, root, segment, seq_len, synth_tokens):
        self._root = os.path.expanduser(root)
        self._seq_len = seq_len
        path = os.path.join(self._root, "wiki.%s.tokens" % segment)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                words = f.read().split()
            vocab = {}
            ids = onp.array([vocab.setdefault(w, len(vocab)) for w in words],
                            dtype="int32")
            self.vocab = vocab
        else:  # zero-egress synthetic corpus (deterministic per segment)
            rng = onp.random.RandomState(hash(segment) % (2 ** 31))
            ids = rng.zipf(1.5, size=synth_tokens).astype("int64")
            ids = onp.clip(ids, 1, self._vocab_size - 1).astype("int32")
            self.vocab = None
        n_seg = (len(ids) - 1) // seq_len
        ids = ids[: n_seg * seq_len + 1]
        self._data = ids[:-1].reshape(n_seg, seq_len)
        self._label = ids[1:].reshape(n_seg, seq_len)

    def __getitem__(self, idx):
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._data)


class WikiText2(_WikiText):
    """ref contrib/data/text.py WikiText2."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "wikitext-2"),
                 segment="train", seq_len=35):
        tokens = {"train": 64 * 1024, "val": 8 * 1024, "test": 8 * 1024}
        super().__init__(root, segment, seq_len,
                         tokens.get(segment, 8 * 1024))


class WikiText103(_WikiText):
    """ref contrib/data/text.py WikiText103 (larger synthetic fallback)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "wikitext-103"),
                 segment="train", seq_len=35):
        tokens = {"train": 256 * 1024, "val": 16 * 1024, "test": 16 * 1024}
        super().__init__(root, segment, seq_len,
                         tokens.get(segment, 16 * 1024))
