"""INT8 quantization flow (ref src/operator/quantization/* +
python/mxnet/contrib/quantization.py).

TPU-native: symmetric int8 quantize/dequantize as XLA convert ops; calibration
(minmax / KL-entropy) over a calibration dataset using the Monitor-style
collection the reference uses (contrib/quantization.py:261).
"""
from __future__ import annotations

import numpy as onp

from .. import ndarray as nd
from ..ndarray import NDArray, _apply

__all__ = ["quantize", "dequantize", "requantize", "calib_minmax", "calib_entropy",
           "quantize_model", "quantize_net", "QuantizedDense",
           "QuantizedDenseBlock", "QuantizedConv2DBlock", "QuantizedConvGroup"]


_INT8_CONV_OK = None


def _native_int8_conv_supported():
    """Probe (once) whether the backend compiles s8 x s8 -> s32 convolution.
    XLA's TPU and CPU backends do; a backend that rejects it routes
    QuantizedConv2DBlock to the QDQ fallback instead of failing at
    inference time. MXTPU_INT8_SIM=1 (the documented escape hatch the
    quantized_* op family honors) forces the fp-simulated path here too —
    checked per call, only the hardware probe is cached."""
    from ..ndarray.contrib import _int8_native
    if not _int8_native():
        return False
    global _INT8_CONV_OK
    if _INT8_CONV_OK is None:
        import jax
        import jax.numpy as jnp
        from jax import lax
        try:
            x = jnp.ones((1, 2, 4, 4), jnp.int8)
            w = jnp.ones((2, 2, 3, 3), jnp.int8)
            dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                            ("NCHW", "OIHW", "NCHW"))
            out = jax.jit(lambda x, w: lax.conv_general_dilated(
                x, w, (1, 1), [(1, 1), (1, 1)], dimension_numbers=dn,
                preferred_element_type=jnp.int32))(x, w)
            out.block_until_ready()
            _INT8_CONV_OK = True
        except Exception:
            _INT8_CONV_OK = False
    return _INT8_CONV_OK


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """ref quantization/quantize.cc — symmetric linear quantization."""
    import jax.numpy as jnp

    if min_range is None or max_range is None:
        a = data.asnumpy()
        min_range, max_range = float(a.min()), float(a.max())
    scale = max(abs(min_range), abs(max_range)) / 127.0 or 1.0

    def fn(x):
        return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)

    q = _apply(fn, data)
    return q, nd.array([min_range]), nd.array([max_range])


def dequantize(data, min_range, max_range, out_type="float32"):
    """ref quantization/dequantize.cc. The quantized-range denominator
    follows the storage dtype: 127 for int8, 2^31-1 for int32 accumulators
    (kInt8Range/kInt32Range in the reference)."""
    import jax.numpy as jnp
    import numpy as onp

    lo = float(min_range.asnumpy()[0]) if isinstance(min_range, NDArray) else min_range
    hi = float(max_range.asnumpy()[0]) if isinstance(max_range, NDArray) else max_range
    denom = 127.0 if onp.dtype(data.dtype).itemsize == 1 else float(2 ** 31 - 1)
    scale = max(abs(lo), abs(hi)) / denom or 1.0
    return _apply(lambda x: x.astype(jnp.float32) * scale, data)


def requantize(data, min_range, max_range, min_calib=None, max_calib=None):
    """ref quantization/requantize.cc — int32 accum → int8."""
    deq = dequantize(data, min_range, max_range)
    return quantize(deq, min_calib, max_calib)


def calib_minmax(activations):
    """Min-max calibration thresholds (ref calibrate.cc minmax mode)."""
    a = onp.concatenate([x.asnumpy().ravel() for x in activations])
    return float(a.min()), float(a.max())


def calib_entropy(activations, num_bins=8001, num_quantized_bins=255):
    """KL-divergence threshold search (ref calibrate.cc entropy mode)."""
    a = onp.abs(onp.concatenate([x.asnumpy().ravel() for x in activations]))
    amax = float(a.max()) or 1.0
    hist, edges = onp.histogram(a, bins=num_bins, range=(0, amax))
    t = _entropy_threshold(hist, edges, num_quantized_bins)
    return -t, t


def _entropy_threshold(hist, edges, num_quantized_bins=255):
    """KL-optimal |threshold| from a |activation| histogram (the op-level
    entry the calibrate_entropy contrib op shares — ref calibrate.cc)."""
    num_bins = len(hist)
    amax = float(edges[-1]) or 1.0
    best_kl, best_t = onp.inf, amax
    for i in range(num_quantized_bins, num_bins, num_bins // 64 or 1):
        t = edges[i]
        clipped = hist[:i].astype(onp.float64)
        p = clipped.copy()
        p[-1] += hist[i:].sum()  # reference dist: outliers clip into last bin
        if p.sum() == 0:
            continue
        # candidate Q: quantize the histogram WITHOUT the outlier lump into
        # num_quantized_bins and expand back. Building Q from p instead
        # makes Q == P exactly at i == num_quantized_bins (KL=0), which
        # always wins and collapses the threshold — the bug the canonical
        # TensorRT/calibrate.cc split of P and Q exists to avoid.
        factor = len(clipped) / num_quantized_bins
        q = onp.zeros_like(clipped)
        for j in range(num_quantized_bins):
            lo, hi = int(j * factor), max(int((j + 1) * factor), int(j * factor) + 1)
            mass = clipped[lo:hi].sum()
            nz = (clipped[lo:hi] > 0).sum()
            if nz:
                q[lo:hi] = onp.where(clipped[lo:hi] > 0, mass / nz, 0)
        p_n = p / p.sum()
        q_n = q / q.sum() if q.sum() else q
        # smoothed KL: positions where P>0 but Q=0 would be infinite —
        # penalize with a floor rather than masking them away (masking
        # hides exactly the clipping error the search must see)
        eps = 1e-12
        mask = p_n > 0
        kl = float((p_n[mask] *
                    onp.log(p_n[mask] / onp.maximum(q_n[mask], eps))).sum())
        if kl < best_kl:
            best_kl, best_t = kl, t
    return best_t


class QuantizedDense:
    """INT8 inference dense layer (ref quantized_fully_connected.cc)."""

    def __init__(self, dense_block, calib_min, calib_max):
        w = dense_block.weight.data()
        self._wq, self._wmin, self._wmax = quantize(w)
        self._bias = dense_block.bias.data() if dense_block.bias is not None else None
        self._cmin, self._cmax = calib_min, calib_max
        self._units = dense_block._units

    def __call__(self, x):
        xq, xmin, xmax = quantize(x, self._cmin, self._cmax)
        import jax.numpy as jnp
        xs = max(abs(self._cmin), abs(self._cmax)) / 127.0 or 1.0
        wmin = float(self._wmin.asnumpy()[0])
        wmax = float(self._wmax.asnumpy()[0])
        ws = max(abs(wmin), abs(wmax)) / 127.0 or 1.0

        from jax import lax

        def fn(xq_, wq_):
            # int8 OPERANDS with an int32 accumulator — the MXU's native 2:1
            # int8 path. (Upcasting the operands to int32 first, as r4 did,
            # runs an int32xint32 matmul and forfeits the speedup.)
            acc = lax.dot_general(xq_, wq_, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            return acc.astype(jnp.float32) * (xs * ws)

        out = _apply(fn, xq, self._wq)
        if self._bias is not None:
            out = out + self._bias
        return out


def quantize_model(net, calib_data=None, calib_mode="minmax", num_calib_batches=4):
    """Per-layer int8 Dense handles (legacy API; quantize_net below swaps a
    whole net in place). Ref contrib/quantization.py quantize_model."""
    from ..gluon import nn

    dense_layers = []

    def walk(b):
        if isinstance(b, nn.Dense):
            dense_layers.append(b)
        for c in b._children.values():
            walk(c)

    walk(net)
    ranges = _calibrate(net, dense_layers, calib_data, calib_mode,
                        num_calib_batches)
    return {layer.name: QuantizedDense(layer, *ranges[id(layer)])
            for layer in dense_layers}


class QuantizedDenseBlock:
    pass  # replaced below (kept for pickle name stability)


def _int8_conv_apply(x, wq, bias, conv_kwargs, in_scale, w_scale,
                     act_type=None, emit_scale=None, fp_dtype=None):
    """Shared s8 x s8 -> s32 conv lowering (the one place the int8 conv is
    written): quantize the input unless it already arrives int8, run the MXU
    conv with an int32 accumulator, rescale + bias in fp32, optionally fuse
    a relu, and either emit int8 at ``emit_scale`` or cast to ``fp_dtype``
    (input dtype when None). Used by QuantizedConv2DBlock and
    QuantizedConvGroup so a fix lands in both."""
    import jax.numpy as jnp
    from jax import lax

    kw = conv_kwargs
    n = len(kw["kernel"])
    stride = tuple(kw.get("stride") or (1,) * n)
    dilate = tuple(kw.get("dilate") or (1,) * n)
    pad = tuple(kw.get("pad") or (0,) * n)
    groups = kw.get("num_group", 1)
    spatial = "".join("DHW"[3 - n:][i] for i in range(n))
    dn_str = ("NC" + spatial, "OI" + spatial, "NC" + spatial)

    def fn(x_, wq_, *b_):
        out_dt = fp_dtype if fp_dtype is not None else x_.dtype
        if x_.dtype != jnp.int8:
            xq = jnp.clip(jnp.round(x_.astype(jnp.float32) / in_scale),
                          -127, 127).astype(jnp.int8)
        else:
            xq = x_
        dn = lax.conv_dimension_numbers(xq.shape, wq_.shape, dn_str)
        acc = lax.conv_general_dilated(
            xq, wq_, window_strides=stride,
            padding=[(p, p) for p in pad], rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (in_scale * w_scale)
        if b_:
            y = y + b_[0].astype(jnp.float32).reshape((1, -1) + (1,) * n)
        if act_type == "relu":
            y = jnp.maximum(y, 0)
        if emit_scale is not None:
            return jnp.clip(jnp.round(y / emit_scale),
                            -127, 127).astype(jnp.int8)
        return y.astype(out_dt)

    return _apply(fn, x, wq, *([bias] if bias is not None else []))


def _make_quantized_classes():
    """Built lazily so contrib.quantization does not import gluon at module
    import (package init order)."""
    global QuantizedDenseBlock, QuantizedConv2DBlock
    from ..gluon.block import HybridBlock

    class _QuantizedDenseBlock(HybridBlock):
        """Int8 Dense replacement — a REAL Block (save/cast/apply keep
        working on the quantized net; this block owns no Parameters)."""

        def __init__(self, dense_block, calib_min, calib_max, **kw):
            super().__init__(**kw)
            self._inner = QuantizedDense(dense_block, calib_min, calib_max)
            self._flatten = getattr(dense_block, "_flatten", True)
            self._act_type = getattr(dense_block, "act_type", None)

        def forward(self, x):
            if self._flatten and len(x.shape) > 2:
                x = x.reshape((x.shape[0], -1))
            out = self._inner(x)
            if self._act_type is not None:
                out = nd.Activation(out, act_type=self._act_type)
            return out

    class _QuantizedConv2DBlock(HybridBlock):
        """Int8 Conv2D replacement. Native path (r5): int8 operands into
        `lax.conv_general_dilated` with an int32 accumulator — the MXU's
        2:1 int8 conv (the analog of the reference's quantized_conv.cc
        int8 kernels) — with quantize/rescale fused around it by XLA.
        Fallback when the backend rejects int8 conv: QDQ (fake-quant)
        around the fp conv — storage numerics int8, compute fp."""

        def __init__(self, conv_block, calib_min, calib_max, **kw):
            super().__init__(**kw)
            w = conv_block.weight.data()
            wq, wmin, wmax = quantize(w)
            self._conv = conv_block  # NOT registered: its hooks/params stay out
            self.__dict__["_conv"] = conv_block
            self._cmin, self._cmax = calib_min, calib_max
            self._native = _native_int8_conv_supported()
            if self._native:
                self._wq = wq
                wl = float(wmin.asnumpy()[0])
                wh = float(wmax.asnumpy()[0])
                self._ws = max(abs(wl), abs(wh)) / 127.0 or 1.0
                self._xs = max(abs(calib_min), abs(calib_max)) / 127.0 or 1.0
            else:
                self._w_deq = dequantize(wq, wmin, wmax)

        def forward(self, x):
            if self._native:
                return self._forward_native(x)
            xq, _xmin, _xmax = quantize(x, self._cmin, self._cmax)
            # dequantize with the calibration FLOATS, not the NDArray
            # wrappers quantize() returns: the wrapper form round-trips
            # through .asnumpy(), which is a TracerArrayConversionError
            # under a jit trace — this QDQ branch must stay servable
            # (EvalStep/BlockServable compile it), not eager-only
            x_deq = dequantize(xq, self._cmin, self._cmax)
            arr = self._conv.weight.data()   # the live NDArray wrapper
            saved = arr._data
            arr._data = self._w_deq._data
            try:
                return self._conv.forward(x_deq)  # bypass hooks/cache
            finally:
                arr._data = saved

        def _forward_native(self, x):
            cb = self._conv
            bias = cb.bias.data() if cb.bias is not None else None
            out = _int8_conv_apply(x, self._wq, bias, cb._kwargs,
                                   self._xs, self._ws)
            if cb.act_type:
                out = nd.Activation(out, act_type=cb.act_type)
            return out

    QuantizedDenseBlock = _QuantizedDenseBlock
    QuantizedConv2DBlock = _QuantizedConv2DBlock
    return _QuantizedDenseBlock, _QuantizedConv2DBlock


QuantizedConv2DBlock = None
QuantizedConvGroup = None


def _make_group_class():
    """Fused [Conv2D (+folded BatchNorm) (+ReLU)] int8 group — the block-level
    analog of the reference's quantize_graph_pass.cc fusion: BN folds into the
    conv weights/bias at quantize time, the conv runs int8 operands with int32
    accumulation on the MXU, and when the NEXT conv group is a direct consumer
    (same HybridSequential, only int8-transparent blocks between) the group
    emits int8 directly so the activation never round-trips HBM at fp width."""
    global QuantizedConvGroup
    if QuantizedConvGroup is not None:
        return QuantizedConvGroup
    from ..gluon.block import HybridBlock

    class _QuantizedConvGroup(HybridBlock):

        def __init__(self, conv_block, bn_block, act_type, in_rng, out_rng,
                     **kw):
            super().__init__(**kw)
            w = conv_block.weight.data()
            self._fp_dtype = str(w.dtype)
            wf = w.asnumpy().astype(onp.float64)
            bias = (conv_block.bias.data().asnumpy().astype(onp.float64)
                    if conv_block.bias is not None
                    else onp.zeros(wf.shape[0], onp.float64))
            if bn_block is not None:
                g = bn_block.gamma.data().asnumpy().astype(onp.float64)
                be = bn_block.beta.data().asnumpy().astype(onp.float64)
                m = bn_block.running_mean.data().asnumpy().astype(onp.float64)
                v = bn_block.running_var.data().asnumpy().astype(onp.float64)
                s = g / onp.sqrt(v + bn_block._epsilon)
                wf = wf * s.reshape((-1,) + (1,) * (wf.ndim - 1))
                bias = (bias - m) * s + be
            ws = (float(onp.abs(wf).max()) / 127.0) or 1.0
            wq = onp.clip(onp.round(wf / ws), -127, 127).astype(onp.int8)
            self._wq = nd.array(wq, dtype="int8")
            self._bias = nd.array(bias.astype(onp.float32))
            self._ws = ws
            self._in_scale = (max(abs(in_rng[0]), abs(in_rng[1])) / 127.0) or 1.0
            self._out_scale = (max(abs(out_rng[0]), abs(out_rng[1])) / 127.0) or 1.0
            self._act_type = act_type
            self._kwargs = dict(conv_block._kwargs)
            self.emit_int8 = False   # set by the pass when a linked consumer exists

        def set_in_scale(self, s):
            self._in_scale = s

        def out_scale(self):
            return self._out_scale

        def forward(self, x):
            act, emit, out_s = self._act_type, self.emit_int8, self._out_scale
            fuse_act = act in (None, "relu")
            out = _int8_conv_apply(
                x, self._wq, self._bias, self._kwargs,
                self._in_scale, self._ws,
                act_type=act if fuse_act else None,
                emit_scale=out_s if (fuse_act and emit) else None,
                fp_dtype=self._fp_dtype)
            if not fuse_act:   # exotic activation: fp act, then (re)quantize
                out = nd.Activation(out, act_type=act)
                if emit:
                    out, _, _ = quantize(out, -127.0 * out_s, 127.0 * out_s)
            return out

    QuantizedConvGroup = _QuantizedConvGroup
    return _QuantizedConvGroup


_PASSTHROUGH_CLS = None
QuantizedResidualBlock = None


def _make_passthrough_class():
    global _PASSTHROUGH_CLS
    if _PASSTHROUGH_CLS is not None:
        return _PASSTHROUGH_CLS
    from ..gluon.block import HybridBlock

    class _Passthrough(HybridBlock):
        """Replaces a BatchNorm/Activation absorbed into a conv group."""

        def forward(self, x):
            return x

    _PASSTHROUGH_CLS = _Passthrough
    return _Passthrough


def _make_residual_class():
    """Int8-aware wrapper for model-zoo V1 residual blocks
    (BasicBlockV1/BottleneckV1: out = relu(body(x) + [downsample](x))).
    The reference's quantize_graph_pass.cc pattern-matches exactly such
    known op sequences; here the wrapper re-expresses the block's forward
    so that (a) an int8 input flows straight into the body's first conv
    group and the downsample conv (no dequantize round-trip — only the
    identity-residual leg rescales, elementwise), and (b) when the next
    block in the stage consumes int8, the post-relu output quantizes once
    at the block boundary — so whole stages chain at 1 byte/elem."""
    global QuantizedResidualBlock
    if QuantizedResidualBlock is not None:
        return QuantizedResidualBlock
    from ..gluon.block import HybridBlock
    GroupCls = _make_group_class()

    class _QuantizedResidualBlock(HybridBlock):

        def __init__(self, inner, in_rng, out_rng, **kw):
            super().__init__(**kw)
            self.inner = inner
            self._in_scale = (max(abs(in_rng[0]), abs(in_rng[1])) / 127.0) or 1.0
            self._out_scale = (max(abs(out_rng[0]), abs(out_rng[1])) / 127.0) or 1.0
            self.emit_int8 = False
            self.set_in_scale(self._in_scale)

        def _entry_groups(self):
            inner = self.inner
            outs = []
            body = getattr(inner, "body", None)
            if body is not None and body._children:
                first = next(iter(body._children.values()))
                if isinstance(first, GroupCls):
                    outs.append(first)
            ds = getattr(inner, "downsample", None)
            if ds is not None and getattr(ds, "_children", None):
                first = next(iter(ds._children.values()))
                if isinstance(first, GroupCls):
                    outs.append(first)
            return outs

        def set_in_scale(self, s):
            self._in_scale = s
            for g in self._entry_groups():
                g.set_in_scale(s)

        def can_accept_int8(self):
            """int8 may only flow in when EVERY entry conv is a quantized
            group: with an excluded (still-fp) body-first or downsample
            conv, raw int8 codes would hit a plain Conv2D unscaled."""
            inner = self.inner
            n_entries = 1 + (getattr(inner, "downsample", None) is not None)
            return len(self._entry_groups()) == n_entries

        def out_scale(self):
            return self._out_scale

        def forward(self, x):
            import jax.numpy as jnp

            inner = self.inner
            ds = getattr(inner, "downsample", None)
            residual = ds(x) if ds is not None else x
            y = inner.body(x)
            if str(residual.dtype) == "int8":   # identity leg: rescale only
                in_s, dt = self._in_scale, str(y.dtype)
                residual = _apply(
                    lambda r: (r.astype(jnp.float32) * in_s).astype(dt),
                    residual)
            out = nd.Activation(y + residual, act_type="relu")
            if self.emit_int8:
                out_s = self._out_scale
                out, _, _ = quantize(out, -127.0 * out_s, 127.0 * out_s)
            return out

    QuantizedResidualBlock = _QuantizedResidualBlock
    return _QuantizedResidualBlock


def _calibrate(net, layers, calib_data, calib_mode, num_calib_batches):
    """Shared hook-based range collection (used by quantize_model and
    quantize_net): returns {id(layer): (lo, hi)}."""
    stats = {}

    def make_hook(key):
        def hook(blk, inputs, output):
            stats.setdefault(key, []).append(inputs[0])
        return hook

    handles = [l.register_forward_hook(make_hook(id(l))) for l in layers]
    try:
        if calib_data is not None:
            for i, batch in enumerate(calib_data):
                if i >= num_calib_batches:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                x = x.data[0] if hasattr(x, "data") else x
                net(x)
    finally:
        for h in handles:
            if h is not None:
                h.detach()
    out = {}
    for l in layers:
        acts = stats.get(id(l))
        if acts:
            out[id(l)] = (calib_entropy(acts) if calib_mode == "entropy"
                          else calib_minmax(acts))
        else:
            out[id(l)] = (-1.0, 1.0)
    return out


def quantize_net(net, calib_data=None, calib_mode="minmax",
                 num_calib_batches=4, quantize_conv=True,
                 exclude_layers=(), fold_bn=True):
    """Graph-level int8 conversion of a Gluon net (ref contrib/
    quantization.py quantize_net + quantize_graph_pass.cc): Dense layers
    become real-int8 matmul blocks; Conv2D layers become, by default
    (``fold_bn=True`` on a backend with s8 conv), fused
    [conv + folded-BN + relu] int8 groups with int8 flowing BETWEEN
    directly-chained groups — the reference pass's fusion + requantize
    chaining. Everything is swapped IN PLACE so the returned net runs
    end-to-end. Calibration collects per-layer input (and group output)
    ranges over ``calib_data`` (minmax or KL-entropy). Compiled-forward
    caches are invalidated after the swap (a hybridized net would otherwise
    keep running its cached fp32 program)."""
    from ..gluon import nn

    if (fold_bn and quantize_conv and _native_int8_conv_supported()
            and not isinstance(net, (nn.Dense, nn.Conv2D))):
        return _quantize_net_groups(net, calib_data, calib_mode,
                                    num_calib_batches, exclude_layers)
    return _quantize_net_legacy(net, calib_data, calib_mode,
                                num_calib_batches, quantize_conv,
                                exclude_layers)


def _quantize_net_legacy(net, calib_data, calib_mode, num_calib_batches,
                         quantize_conv, exclude_layers):
    """Per-block swap (no BN folding, no inter-layer int8): Dense -> int8
    matmul block, Conv2D -> native-int8 (or QDQ-fallback) block."""
    from ..gluon import nn
    QD, QC = _make_quantized_classes()

    def is_target(b):
        if isinstance(b, nn.Dense) and b.name not in exclude_layers:
            return "dense"
        if quantize_conv and isinstance(b, nn.Conv2D) and \
                b.name not in exclude_layers:
            return "conv"
        return None

    root_kind = is_target(net)
    targets = []  # (parent, child_key, block, kind)

    def walk(b):
        for key, child in list(b._children.items()):
            kind = is_target(child)
            if kind:
                targets.append((b, key, child, kind))
            else:
                walk(child)

    if not root_kind:
        walk(net)
    layers = [net] if root_kind else [t[2] for t in targets]
    ranges = _calibrate(net, layers, calib_data, calib_mode,
                        num_calib_batches)

    def wrap(block, kind):
        lo, hi = ranges[id(block)]
        return QD(block, lo, hi) if kind == "dense" else QC(block, lo, hi)

    if root_kind:
        return wrap(net, root_kind)
    for parent, key, block, kind in targets:
        q = wrap(block, kind)
        parent._children[key] = q
        # attribute references (self.fc = Dense(...)) must follow too
        for attr, val in list(vars(parent).items()):
            if val is block:
                object.__setattr__(parent, attr, q)

    _clear_forward_caches(net)
    return net


def _clear_forward_caches(net):
    """Invalidate compiled-forward caches after a swap: a hybridized net
    would otherwise keep executing the cached fp32 program."""
    if hasattr(net, "_cached_fn"):
        net._cached_fn = None
    for c in net._children.values():
        _clear_forward_caches(c)


def _quantize_net_groups(net, calib_data, calib_mode, num_calib_batches,
                         exclude_layers):
    """The fused-group pass (ref quantize_graph_pass.cc analog):

    1. Walk every container. Inside a HybridSequential (child order ==
       dataflow), each Conv2D absorbs a directly-following BatchNorm
       (folded into weights/bias) and relu Activation into ONE group; in
       non-sequential parents each Conv2D becomes a standalone fp-in/fp-out
       group (their forward() wiring is opaque, so no folding/chaining).
    2. Calibrate group INPUT and OUTPUT ranges, V1-residual-block in/out
       ranges, and Dense input ranges in one hooked eager walk.
    3. Swap groups in (absorbed BN/Activation blocks become passthroughs)
       and wrap V1 residual blocks int8-aware, then link chains over the
       swapped tree: when only int8-transparent blocks (max-pool,
       passthroughs) separate two int8-capable nodes in a sequential —
       where a nested HybridSequential's endpoints count as its first/last
       child's, so whole stages chain — the producer emits int8 and the
       consumer reads it with the producer's output scale. Chained
       activations cross HBM at 1 byte/elem and never re-quantize.
    """
    from ..gluon import nn
    from ..gluon.model_zoo import vision as _zoo
    GroupCls = _make_group_class()
    ResCls = _make_residual_class()
    Pass = _make_passthrough_class()
    QD, _ = _make_quantized_classes()
    res_types = (_zoo.BasicBlockV1, _zoo.BottleneckV1)

    groups = []         # group descriptors
    res_blocks = []     # (parent, key, block)
    dense_targets = []  # (parent, key, block)

    def walk(parent):
        seq = isinstance(parent, nn.HybridSequential)
        kids = list(parent._children.items())
        i = 0
        while i < len(kids):
            key, child = kids[i]
            if isinstance(child, nn.Dense) and child.name not in exclude_layers:
                dense_targets.append((parent, key, child))
                i += 1
                continue
            if isinstance(child, nn.Conv2D) and child.name not in exclude_layers:
                bn = act = None
                j = i + 1
                if seq and j < len(kids) \
                        and isinstance(kids[j][1], nn.BatchNorm) \
                        and kids[j][1]._axis == 1:
                    bn = kids[j]
                    j += 1
                if seq and j < len(kids) \
                        and isinstance(kids[j][1], nn.Activation) \
                        and kids[j][1]._act_type == "relu" \
                        and child.act_type is None:
                    act = kids[j]
                    j += 1
                groups.append({"parent": parent, "key": key, "conv": child,
                               "bn": bn, "act": act})
                i = j
                continue
            if isinstance(child, res_types) and child.name not in exclude_layers:
                res_blocks.append((parent, key, child))
            walk(child)
            i += 1

    walk(net)

    # --- calibration: group input/output + dense input ranges, one walk ---
    stats_in, stats_out = {}, {}

    def in_hook(key):
        def hook(blk, inputs, output):
            stats_in.setdefault(key, []).append(inputs[0])
        return hook

    def out_hook(key):
        def hook(blk, inputs, output):
            stats_out.setdefault(key, []).append(output)
        return hook

    handles = []
    for gi, g in enumerate(groups):
        handles.append(g["conv"].register_forward_hook(in_hook(("g", gi))))
        last = (g["act"] or g["bn"] or (None, g["conv"]))[1]
        handles.append(last.register_forward_hook(out_hook(("g", gi))))
    for parent, key, blk in res_blocks:
        handles.append(blk.register_forward_hook(in_hook(("r", id(blk)))))
        handles.append(blk.register_forward_hook(out_hook(("r", id(blk)))))
    for parent, key, blk in dense_targets:
        handles.append(blk.register_forward_hook(in_hook(("d", id(blk)))))
    try:
        if calib_data is not None:
            for i, batch in enumerate(calib_data):
                if i >= num_calib_batches:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                x = x.data[0] if hasattr(x, "data") else x
                net(x)
    finally:
        for h in handles:
            if h is not None:
                h.detach()

    calib = calib_entropy if calib_mode == "entropy" else calib_minmax

    def rng(stats, key):
        acts = stats.get(key)
        return calib(acts) if acts else (-1.0, 1.0)

    # --- build + swap ---
    for gi, g in enumerate(groups):
        obj = GroupCls(g["conv"], g["bn"] and g["bn"][1],
                       g["act"][1]._act_type if g["act"] else g["conv"].act_type,
                       rng(stats_in, ("g", gi)), rng(stats_out, ("g", gi)))
        parent = g["parent"]
        parent._children[g["key"]] = obj
        for attr, val in list(vars(parent).items()):
            if val is g["conv"]:
                object.__setattr__(parent, attr, obj)
        for absorbed in (g["bn"], g["act"]):
            if absorbed is not None:
                parent._children[absorbed[0]] = Pass()

    for parent, key, blk in res_blocks:
        obj = ResCls(blk, rng(stats_in, ("r", id(blk))),
                     rng(stats_out, ("r", id(blk))))
        parent._children[key] = obj
        for attr, val in list(vars(parent).items()):
            if val is blk:
                object.__setattr__(parent, attr, obj)

    for parent, key, blk in dense_targets:
        lo, hi = rng(stats_in, ("d", id(blk)))
        q = QD(blk, lo, hi)
        parent._children[key] = q
        for attr, val in list(vars(parent).items()):
            if val is blk:
                object.__setattr__(parent, attr, q)

    _link_chains(net)
    _clear_forward_caches(net)
    return net


def _link_chains(root):
    """Generic int8 chain linking over the already-swapped tree: inside every
    HybridSequential, walk children in dataflow order; a producer whose exit
    node is int8-capable and a consumer whose entry node is int8-capable,
    separated only by int8-transparent blocks (max-pool preserves values and
    scale on int8; passthroughs are identity), get linked — the producer
    emits int8 and the consumer's input scale becomes the producer's output
    scale (same tensor, so the wiring is exact, not just calibrated-equal).
    A nested HybridSequential's entry/exit are its first/last child's, so
    model-zoo stages chain end-to-end through block wrappers."""
    from ..gluon import nn
    GroupCls = _make_group_class()
    ResCls = _make_residual_class()
    Pass = _make_passthrough_class()

    def transparent(b):
        return isinstance(b, (Pass, nn.MaxPool2D))

    def entry(b):
        if isinstance(b, GroupCls):
            return b
        if isinstance(b, ResCls):
            # an excluded (still-fp) entry conv means raw int8 codes would
            # hit a plain Conv2D — such a wrapper cannot consume int8
            return b if b.can_accept_int8() else None
        if isinstance(b, nn.HybridSequential):
            for c in b._children.values():
                if transparent(c):   # int8 passes through unchanged
                    continue
                return entry(c)
        return None

    def exit_(b):
        if isinstance(b, (GroupCls, ResCls)):
            return b
        if isinstance(b, nn.HybridSequential):
            for c in reversed(list(b._children.values())):
                if transparent(c):   # trailing pool/passthrough keeps int8
                    continue
                return exit_(c)
        return None

    def link(parent):
        if isinstance(parent, GroupCls):
            return
        if isinstance(parent, ResCls):
            # the wrapper manages its own entry/exit scales; its body still
            # chains internally (conv groups feed each other) — the last
            # body group keeps fp out since the residual add consumes it
            body = getattr(parent.inner, "body", None)
            if body is not None:
                link(body)
            return
        if isinstance(parent, nn.HybridSequential):
            prev_exit = None
            for child in parent._children.values():
                if isinstance(child, (Pass, nn.MaxPool2D)):
                    continue   # transparent: chain continues across
                e = entry(child)
                if prev_exit is not None and e is not None:
                    prev_exit.emit_int8 = True
                    e.set_in_scale(prev_exit.out_scale())
                prev_exit = exit_(child)
        for child in parent._children.values():
            link(child)

    link(root)
