"""INT8 quantization flow (ref src/operator/quantization/* +
python/mxnet/contrib/quantization.py).

TPU-native: symmetric int8 quantize/dequantize as XLA convert ops; calibration
(minmax / KL-entropy) over a calibration dataset using the Monitor-style
collection the reference uses (contrib/quantization.py:261).
"""
from __future__ import annotations

import numpy as onp

from .. import ndarray as nd
from ..ndarray import NDArray, _apply

__all__ = ["quantize", "dequantize", "requantize", "calib_minmax", "calib_entropy",
           "quantize_model", "quantize_net", "QuantizedDense",
           "QuantizedDenseBlock", "QuantizedConv2DBlock"]


def quantize(data, min_range=None, max_range=None, out_type="int8"):
    """ref quantization/quantize.cc — symmetric linear quantization."""
    import jax.numpy as jnp

    if min_range is None or max_range is None:
        a = data.asnumpy()
        min_range, max_range = float(a.min()), float(a.max())
    scale = max(abs(min_range), abs(max_range)) / 127.0 or 1.0

    def fn(x):
        return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)

    q = _apply(fn, data)
    return q, nd.array([min_range]), nd.array([max_range])


def dequantize(data, min_range, max_range, out_type="float32"):
    """ref quantization/dequantize.cc. The quantized-range denominator
    follows the storage dtype: 127 for int8, 2^31-1 for int32 accumulators
    (kInt8Range/kInt32Range in the reference)."""
    import jax.numpy as jnp
    import numpy as onp

    lo = float(min_range.asnumpy()[0]) if isinstance(min_range, NDArray) else min_range
    hi = float(max_range.asnumpy()[0]) if isinstance(max_range, NDArray) else max_range
    denom = 127.0 if onp.dtype(data.dtype).itemsize == 1 else float(2 ** 31 - 1)
    scale = max(abs(lo), abs(hi)) / denom or 1.0
    return _apply(lambda x: x.astype(jnp.float32) * scale, data)


def requantize(data, min_range, max_range, min_calib=None, max_calib=None):
    """ref quantization/requantize.cc — int32 accum → int8."""
    deq = dequantize(data, min_range, max_range)
    return quantize(deq, min_calib, max_calib)


def calib_minmax(activations):
    """Min-max calibration thresholds (ref calibrate.cc minmax mode)."""
    a = onp.concatenate([x.asnumpy().ravel() for x in activations])
    return float(a.min()), float(a.max())


def calib_entropy(activations, num_bins=8001, num_quantized_bins=255):
    """KL-divergence threshold search (ref calibrate.cc entropy mode)."""
    a = onp.abs(onp.concatenate([x.asnumpy().ravel() for x in activations]))
    amax = float(a.max()) or 1.0
    hist, edges = onp.histogram(a, bins=num_bins, range=(0, amax))
    t = _entropy_threshold(hist, edges, num_quantized_bins)
    return -t, t


def _entropy_threshold(hist, edges, num_quantized_bins=255):
    """KL-optimal |threshold| from a |activation| histogram (the op-level
    entry the calibrate_entropy contrib op shares — ref calibrate.cc)."""
    num_bins = len(hist)
    amax = float(edges[-1]) or 1.0
    best_kl, best_t = onp.inf, amax
    for i in range(num_quantized_bins, num_bins, num_bins // 64 or 1):
        t = edges[i]
        clipped = hist[:i].astype(onp.float64)
        p = clipped.copy()
        p[-1] += hist[i:].sum()  # reference dist: outliers clip into last bin
        if p.sum() == 0:
            continue
        # candidate Q: quantize the histogram WITHOUT the outlier lump into
        # num_quantized_bins and expand back. Building Q from p instead
        # makes Q == P exactly at i == num_quantized_bins (KL=0), which
        # always wins and collapses the threshold — the bug the canonical
        # TensorRT/calibrate.cc split of P and Q exists to avoid.
        factor = len(clipped) / num_quantized_bins
        q = onp.zeros_like(clipped)
        for j in range(num_quantized_bins):
            lo, hi = int(j * factor), max(int((j + 1) * factor), int(j * factor) + 1)
            mass = clipped[lo:hi].sum()
            nz = (clipped[lo:hi] > 0).sum()
            if nz:
                q[lo:hi] = onp.where(clipped[lo:hi] > 0, mass / nz, 0)
        p_n = p / p.sum()
        q_n = q / q.sum() if q.sum() else q
        # smoothed KL: positions where P>0 but Q=0 would be infinite —
        # penalize with a floor rather than masking them away (masking
        # hides exactly the clipping error the search must see)
        eps = 1e-12
        mask = p_n > 0
        kl = float((p_n[mask] *
                    onp.log(p_n[mask] / onp.maximum(q_n[mask], eps))).sum())
        if kl < best_kl:
            best_kl, best_t = kl, t
    return best_t


class QuantizedDense:
    """INT8 inference dense layer (ref quantized_fully_connected.cc)."""

    def __init__(self, dense_block, calib_min, calib_max):
        w = dense_block.weight.data()
        self._wq, self._wmin, self._wmax = quantize(w)
        self._bias = dense_block.bias.data() if dense_block.bias is not None else None
        self._cmin, self._cmax = calib_min, calib_max
        self._units = dense_block._units

    def __call__(self, x):
        xq, xmin, xmax = quantize(x, self._cmin, self._cmax)
        import jax.numpy as jnp
        xs = max(abs(self._cmin), abs(self._cmax)) / 127.0 or 1.0
        wmin = float(self._wmin.asnumpy()[0])
        wmax = float(self._wmax.asnumpy()[0])
        ws = max(abs(wmin), abs(wmax)) / 127.0 or 1.0

        def fn(xq_, wq_):
            acc = jnp.matmul(xq_.astype(jnp.int32), wq_.astype(jnp.int32).T)
            return acc.astype(jnp.float32) * (xs * ws)

        out = _apply(fn, xq, self._wq)
        if self._bias is not None:
            out = out + self._bias
        return out


def quantize_model(net, calib_data=None, calib_mode="minmax", num_calib_batches=4):
    """Per-layer int8 Dense handles (legacy API; quantize_net below swaps a
    whole net in place). Ref contrib/quantization.py quantize_model."""
    from ..gluon import nn

    dense_layers = []

    def walk(b):
        if isinstance(b, nn.Dense):
            dense_layers.append(b)
        for c in b._children.values():
            walk(c)

    walk(net)
    ranges = _calibrate(net, dense_layers, calib_data, calib_mode,
                        num_calib_batches)
    return {layer.name: QuantizedDense(layer, *ranges[id(layer)])
            for layer in dense_layers}


class QuantizedDenseBlock:
    pass  # replaced below (kept for pickle name stability)


def _make_quantized_classes():
    """Built lazily so contrib.quantization does not import gluon at module
    import (package init order)."""
    global QuantizedDenseBlock, QuantizedConv2DBlock
    from ..gluon.block import HybridBlock

    class _QuantizedDenseBlock(HybridBlock):
        """Int8 Dense replacement — a REAL Block (save/cast/apply keep
        working on the quantized net; this block owns no Parameters)."""

        def __init__(self, dense_block, calib_min, calib_max, **kw):
            super().__init__(**kw)
            self._inner = QuantizedDense(dense_block, calib_min, calib_max)
            self._flatten = getattr(dense_block, "_flatten", True)
            self._act_type = getattr(dense_block, "act_type", None)

        def forward(self, x):
            if self._flatten and len(x.shape) > 2:
                x = x.reshape((x.shape[0], -1))
            out = self._inner(x)
            if self._act_type is not None:
                out = nd.Activation(out, act_type=self._act_type)
            return out

    class _QuantizedConv2DBlock(HybridBlock):
        """QDQ (fake-quant) int8 Conv2D replacement: weights and
        activations quantize->dequantize around the fp conv. The reference
        runs native int8 conv kernels (quantized_conv.cc); XLA has no int8
        conv path, so storage numerics are int8 while the MXU conv stays
        bf16/fp32 — documented divergence."""

        def __init__(self, conv_block, calib_min, calib_max, **kw):
            super().__init__(**kw)
            w = conv_block.weight.data()
            wq, wmin, wmax = quantize(w)
            self._w_deq = dequantize(wq, wmin, wmax)
            self._conv = conv_block  # NOT registered: its hooks/params stay out
            self.__dict__["_conv"] = conv_block
            self._cmin, self._cmax = calib_min, calib_max

        def forward(self, x):
            xq, xmin, xmax = quantize(x, self._cmin, self._cmax)
            x_deq = dequantize(xq, xmin, xmax)
            arr = self._conv.weight.data()   # the live NDArray wrapper
            saved = arr._data
            arr._data = self._w_deq._data
            try:
                return self._conv.forward(x_deq)  # bypass hooks/cache
            finally:
                arr._data = saved

    QuantizedDenseBlock = _QuantizedDenseBlock
    QuantizedConv2DBlock = _QuantizedConv2DBlock
    return _QuantizedDenseBlock, _QuantizedConv2DBlock


QuantizedConv2DBlock = None


def _calibrate(net, layers, calib_data, calib_mode, num_calib_batches):
    """Shared hook-based range collection (used by quantize_model and
    quantize_net): returns {id(layer): (lo, hi)}."""
    stats = {}

    def make_hook(key):
        def hook(blk, inputs, output):
            stats.setdefault(key, []).append(inputs[0])
        return hook

    handles = [l.register_forward_hook(make_hook(id(l))) for l in layers]
    try:
        if calib_data is not None:
            for i, batch in enumerate(calib_data):
                if i >= num_calib_batches:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                x = x.data[0] if hasattr(x, "data") else x
                net(x)
    finally:
        for h in handles:
            if h is not None:
                h.detach()
    out = {}
    for l in layers:
        acts = stats.get(id(l))
        if acts:
            out[id(l)] = (calib_entropy(acts) if calib_mode == "entropy"
                          else calib_minmax(acts))
        else:
            out[id(l)] = (-1.0, 1.0)
    return out


def quantize_net(net, calib_data=None, calib_mode="minmax",
                 num_calib_batches=4, quantize_conv=True,
                 exclude_layers=()):
    """Graph-level int8 conversion of a Gluon net (ref contrib/
    quantization.py quantize_net): Dense layers become real-int8 matmul
    blocks, Conv2D layers become QDQ blocks, swapped IN PLACE so the
    returned net runs end-to-end. Calibration collects per-layer input
    ranges over ``calib_data`` (minmax or KL-entropy). Compiled-forward
    caches are invalidated after the swap (a hybridized net would otherwise
    keep running its cached fp32 program)."""
    from ..gluon import nn
    QD, QC = _make_quantized_classes()

    def is_target(b):
        if isinstance(b, nn.Dense) and b.name not in exclude_layers:
            return "dense"
        if quantize_conv and isinstance(b, nn.Conv2D) and \
                b.name not in exclude_layers:
            return "conv"
        return None

    root_kind = is_target(net)
    targets = []  # (parent, child_key, block, kind)

    def walk(b):
        for key, child in list(b._children.items()):
            kind = is_target(child)
            if kind:
                targets.append((b, key, child, kind))
            else:
                walk(child)

    if not root_kind:
        walk(net)
    layers = [net] if root_kind else [t[2] for t in targets]
    ranges = _calibrate(net, layers, calib_data, calib_mode,
                        num_calib_batches)

    def wrap(block, kind):
        lo, hi = ranges[id(block)]
        return QD(block, lo, hi) if kind == "dense" else QC(block, lo, hi)

    if root_kind:
        return wrap(net, root_kind)
    for parent, key, block, kind in targets:
        q = wrap(block, kind)
        parent._children[key] = q
        # attribute references (self.fc = Dense(...)) must follow too
        for attr, val in list(vars(parent).items()):
            if val is block:
                object.__setattr__(parent, attr, q)

    # invalidate compiled-forward caches everywhere: a hybridized net would
    # otherwise keep executing the cached fp32 program for known shapes
    def clear(b):
        if hasattr(b, "_cached_fn"):
            b._cached_fn = None
        for c in b._children.values():
            clear(c)

    clear(net)
    return net
