"""AMP — automatic mixed precision (ref python/mxnet/contrib/amp/amp.py +
src/nnvm/low_precision_pass.cc).

TPU-native: the target dtype is bf16 (native on the MXU — no loss-scaling
subtleties of fp16). ``convert_model``/``convert_hybrid_block`` apply the
cast-list policy: compute-heavy ops run in bf16, reductions/norms stay fp32
(our BatchNorm/LayerNorm already compute statistics in fp32 internally).
A dynamic loss scaler is provided for fp16-style flows anyway (API parity).
"""
from __future__ import annotations

from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["init", "init_trainer", "convert_model", "convert_hybrid_block",
           "scale_loss", "unscale", "LossScaler",
           "FP16_FP32_FUNCS", "FP16_FUNCS", "FP32_FUNCS"]

# cast-list parity with the reference AMP lists (indicative subsets)
FP16_FUNCS = ["FullyConnected", "Convolution", "Deconvolution", "batch_dot", "dot"]
FP32_FUNCS = ["softmax", "log_softmax", "norm", "mean", "sum", "BatchNorm",
              "LayerNorm", "SoftmaxOutput", "exp", "log"]
FP16_FP32_FUNCS = ["relu", "sigmoid", "tanh", "add", "subtract", "multiply"]

_INITIALIZED = {"flag": False, "dtype": "bfloat16"}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """ref amp.py init — record the policy (bf16 by default on TPU)."""
    _INITIALIZED["flag"] = True
    _INITIALIZED["dtype"] = "bfloat16" if target_dtype in (
        "float16", "bfloat16") else target_dtype


def init_trainer(trainer):
    """ref amp.py init_trainer — enable fp32 master weights."""
    trainer._optimizer.multi_precision = True


class LossScaler:
    """Dynamic loss scaling (ref amp loss scaler) — rarely needed for bf16."""

    def __init__(self, init_scale=2 ** 16, scale_factor=2.0, scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def scale(self, loss):
        return loss * self.loss_scale

    def unscale(self, grads):
        inv = 1.0 / self.loss_scale
        for g in grads:
            g._data = (g * inv)._data

    def check_and_update(self, grads):
        """Returns True if grads are finite (step should apply).

        The finiteness check is ONE fused on-device reduction over the
        whole grad list with a single scalar device->host transfer — a
        per-gradient ``.asnumpy()`` round-trip here would sync the
        pipeline once per parameter, every step (the shape mxtpulint
        R001 flags in hot paths)."""
        import jax.numpy as jnp
        leaves = [getattr(g, "_data", g) for g in grads]
        if leaves:
            all_finite = jnp.array(True)
            for leaf in leaves:
                all_finite = jnp.logical_and(
                    all_finite,
                    jnp.all(jnp.isfinite(jnp.asarray(leaf,
                                                     dtype=jnp.float32))))
            # reviewed sync point: the one scalar transfer of the check
            finite = bool(all_finite)
        else:
            finite = True
        if finite:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
        else:
            self.loss_scale = max(1.0, self.loss_scale / self._scale_factor)
            self._unskipped = 0
        return finite


def scale_loss(loss, trainer):
    """Context-free helper mirroring amp.scale_loss."""
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        scaler = LossScaler()
        trainer._amp_loss_scaler = scaler
    return scaler.scale(loss)


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is not None:
        grads = [p.grad() for p in trainer._params if p.grad_req != "null"]
        scaler.unscale(grads)


def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  **kwargs):
    """Symbolic AMP conversion: cast params to bf16, keep aux fp32
    (ref amp.py convert_model / ReducePrecision pass)."""
    new_args = {k: v.astype(target_dtype) for k, v in arg_params.items()}
    return sym, new_args, dict(aux_params)


def convert_hybrid_block(block, target_dtype="bfloat16", ctx=None, **kwargs):
    """Gluon AMP conversion (ref amp.py convert_hybrid_block): bf16 params,
    fp32 norm layers (Block.cast already special-cases BatchNorm)."""
    block.cast(target_dtype)
    return block
