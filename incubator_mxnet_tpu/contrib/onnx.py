"""ONNX interop (ref python/mxnet/contrib/onnx/ mx2onnx + onnx2mx).

REAL .onnx emission/parsing with no dependency on the `onnx` package (absent
in this image): contrib.onnx_proto implements the protobuf wire format for
the ONNX IR subset used here. Exported files are standard ModelProto
(ir_version 8, opset 17 — LayerNormalization's floor) loadable by
onnxruntime/netron; import maps ONNX nodes back onto mx.sym ops and
round-trips numerically (tests/test_onnx.py).

Supported ops — the model-zoo CNN surface: Conv, Gemm (FullyConnected),
BatchNormalization, Relu/Sigmoid/Tanh/Softplus, MaxPool/AveragePool/
GlobalAveragePool/GlobalMaxPool, Flatten, Softmax, Dropout, Concat, Add/Sub/
Mul/Div, MatMul, Exp/Log/Sqrt/Neg/Abs, Reshape, Transpose, Clip —
plus (r5) the transformer/RNN surface so this repo's own BERT/GPT-shaped
symbolic graphs and fused-RNN layers round-trip: Embedding<->Gather,
LayerNorm<->LayerNormalization (opset 17), batch_dot<->MatMul (with
transpose fix-ups), gelu<->Erf decomposition, LeakyReLU family
(LeakyRelu/Elu/Selu/PRelu), Where, Erf, Unsqueeze/Squeeze, Slice, Cast,
Pow, scalar arithmetic (_*_scalar <-> Add/Sub/Mul/Div/Pow with a scalar
initializer), and the monolithic RNN op <-> ONNX LSTM/GRU/RNN nodes
(per-layer stack, cuDNN ifgo->onnx iofc gate repacking, D in {1,2}).

Known subset limits (vs the reference's ~100-op mx2onnx table): no
resize/interp, no boolean reductions, RNN export requires the packed
parameter vector to be an initializer and state_outputs=False.
"""
from __future__ import annotations

import numpy as onp

from . import onnx_proto as P

__all__ = ["export_model", "import_model", "get_model_metadata"]

_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus"}
_ELEM = {"add": "Add", "elemwise_add": "Add", "broadcast_add": "Add",
         "subtract": "Sub", "elemwise_sub": "Sub", "broadcast_sub": "Sub",
         "multiply": "Mul", "elemwise_mul": "Mul", "broadcast_mul": "Mul",
         "divide": "Div", "elemwise_div": "Div", "broadcast_div": "Div",
         "_plus": "Add", "_minus": "Sub", "_mul": "Mul", "_div": "Div",
         "_pow": "Pow", "power": "Pow", "broadcast_power": "Pow",
         "maximum": "Max", "_maximum": "Max", "broadcast_maximum": "Max",
         "minimum": "Min", "_minimum": "Min", "broadcast_minimum": "Min"}
_UNARY = {"exp": "Exp", "log": "Log", "sqrt": "Sqrt", "negative": "Neg",
          "abs": "Abs", "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "identity": "Identity", "flatten": "Flatten", "erf": "Erf"}
_SCALAR = {"_plus_scalar": "Add", "_minus_scalar": "Sub",
           "_mul_scalar": "Mul", "_div_scalar": "Div",
           "_power_scalar": "Pow", "_pow_scalar": "Pow"}
# comparisons return float 0/1 masks in mx semantics; exported as the ONNX
# bool comparison + Cast back to float so downstream arithmetic stays valid
_CMP_SCALAR = {"_greater_scalar": "Greater", "_lesser_scalar": "Less",
               "_greater_equal_scalar": "GreaterOrEqual",
               "_lesser_equal_scalar": "LessOrEqual",
               "_equal_scalar": "Equal", "_not_equal_scalar": None}
_CMP = {"broadcast_greater": "Greater", "broadcast_lesser": "Less",
        "broadcast_greater_equal": "GreaterOrEqual",
        "broadcast_lesser_equal": "LessOrEqual",
        "broadcast_equal": "Equal",
        # Symbol operator sugar traces two-symbol comparisons as _greater
        # etc. (symbol/symbol.py __gt__/__ge__/__lt__/__le__)
        "_greater": "Greater", "_lesser": "Less",
        "_greater_equal": "GreaterOrEqual", "_lesser_equal": "LessOrEqual",
        "_equal": "Equal", "greater": "Greater", "lesser": "Less",
        "greater_equal": "GreaterOrEqual", "lesser_equal": "LessOrEqual",
        "equal": "Equal"}
# our cuDNN-layout gate order -> ONNX gate order, as a block permutation
# along the (G*H, *) axis:  lstm ifgo -> iofc;  gru rzn -> zrn (and ONNX
# linear_before_reset=1 matches the cuDNN recurrence we implement)
_GATE_PERM = {"lstm": (0, 3, 1, 2), "gru": (1, 0, 2),
              "rnn_relu": (0,), "rnn_tanh": (0,)}
_GATE_UNPERM = {m: tuple(onp.argsort(p)) for m, p in _GATE_PERM.items()}
_ONNX_RNN_OP = {"lstm": "LSTM", "gru": "GRU",
                "rnn_relu": "RNN", "rnn_tanh": "RNN"}


def export_model(sym, params, input_shape, input_type="float32",
                 onnx_file_path="model.onnx", verbose=False):
    """Symbol + params → .onnx file (ref mx2onnx/export_model.py).

    input_shape: one shape tuple (single data input) or list of tuples
    matching the non-parameter arguments in order.
    """
    from ..ndarray import NDArray

    if isinstance(input_shape, tuple):
        input_shape = [input_shape]
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}

    nodes, initializers, extra_inits = [], [], {}
    fix_gamma_ones = []  # (ones_init_name, gamma_value_name) for BatchNorm
    arg_names = sym.list_arguments()
    data_names = [n for n in arg_names if n not in params]
    if len(data_names) != len(input_shape):
        raise ValueError("input_shape entries (%d) must match data inputs %s"
                         % (len(input_shape), data_names))

    # shape hints survive pops during emit (the RNN branch removes its
    # repacked parameter vector from params, but infer_shape still needs
    # every original shape)
    shape_hints = {k: tuple(v.shape) for k, v in params.items()}
    name_of = {}
    counter = [0]

    def fresh(prefix):
        counter[0] += 1
        return "%s_%d" % (prefix, counter[0])

    def emit(s):
        """Returns the output value name for node s."""
        base = getattr(s, "_base", None) or s
        if id(base) in name_of:
            return name_of[id(base)]
        if base.is_var:
            name_of[id(base)] = base.name
            return base.name
        ins = [emit(i) for i in base._inputs]
        op, kw = base._op_name, base._kwargs
        out = base.name
        if op == "FullyConnected":
            a = ins[0]
            if kw.get("flatten", True):
                f = fresh("flat")
                nodes.append(P.node("Flatten", [a], [f], f,
                                    [P.attr_int("axis", 1)]))
                a = f
                attrs = [P.attr_float("alpha", 1.0),
                         P.attr_float("beta", 1.0), P.attr_int("transB", 1)]
                gemm_in = [a, ins[1]] + (ins[2:3]
                                         if not kw.get("no_bias") else [])
                nodes.append(P.node("Gemm", gemm_in, [out], out, attrs))
            else:
                # ONNX Gemm is strictly 2-D; the flatten=False (rank-
                # preserving) FC becomes MatMul(x, W^T) + bias — runtimes
                # constant-fold the weight Transpose
                wt = fresh("fc_wT")
                nodes.append(P.node("Transpose", [ins[1]], [wt], wt,
                                    [P.attr_ints("perm", (1, 0))]))
                if kw.get("no_bias"):
                    nodes.append(P.node("MatMul", [a, wt], [out], out))
                else:
                    mm = fresh("fc_mm")
                    nodes.append(P.node("MatMul", [a, wt], [mm], mm))
                    nodes.append(P.node("Add", [mm, ins[2]], [out], out))
        elif op == "Convolution":
            attrs = [P.attr_ints("kernel_shape", kw["kernel"]),
                     P.attr_ints("strides", kw.get("stride", (1, 1))),
                     P.attr_ints("pads", tuple(kw.get("pad", (0, 0))) * 2),
                     P.attr_ints("dilations", kw.get("dilate", (1, 1))),
                     P.attr_int("group", kw.get("num_group", 1))]
            cin = ins[:2] + (ins[2:3] if not kw.get("no_bias") else [])
            nodes.append(P.node("Conv", cin, [out], out, attrs))
        elif op == "BatchNorm":
            attrs = [P.attr_float("epsilon", kw.get("eps", 1e-5)),
                     P.attr_float("momentum", kw.get("momentum", 0.9))]
            # mx order: data,gamma,beta,mean,var == onnx: X,scale,B,mean,var.
            # fix_gamma=True (mx default) means gamma is IGNORED in compute —
            # ONNX has no such flag, so emit a ones scale to match the math
            if kw.get("fix_gamma", True):
                ones_name = fresh("bn_scale_ones")
                extra_inits[ones_name] = None  # filled after shapes known
                fix_gamma_ones.append((ones_name, ins[1]))
                ins = [ins[0], ones_name] + ins[2:]
            nodes.append(P.node("BatchNormalization", ins[:5], [out], out,
                                attrs))
        elif op == "Activation":
            nodes.append(P.node(_ACT[kw.get("act_type", "relu")], ins, [out],
                                out))
        elif op == "Pooling":
            ptype = kw.get("pool_type", "max")
            if kw.get("global_pool"):
                o = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
                nodes.append(P.node(o, ins, [out], out))
            else:
                o = "MaxPool" if ptype == "max" else "AveragePool"
                k = tuple(kw["kernel"])
                attrs = [P.attr_ints("kernel_shape", k),
                         P.attr_ints("strides", kw.get("stride") or (1,) * len(k)),
                         P.attr_ints("pads",
                                     tuple(kw.get("pad") or (0,) * len(k)) * 2)]
                if o == "AveragePool":
                    attrs.append(P.attr_int("count_include_pad", 1))
                nodes.append(P.node(o, ins, [out], out, attrs))
        elif op in ("softmax", "SoftmaxOutput", "log_softmax"):
            nodes.append(P.node("Softmax", ins[:1], [out], out,
                                [P.attr_int("axis", kw.get("axis", -1))]))
            if op == "log_softmax":
                lg = fresh("log")
                nodes.append(P.node("Log", [out], [lg], lg))
                name_of[id(base)] = lg
                return lg
        elif op == "concat":
            nodes.append(P.node("Concat", ins, [out], out,
                                [P.attr_int("axis", kw.get("dim",
                                                           kw.get("axis", 1)))]))
        elif op == "Dropout":
            nodes.append(P.node("Dropout", ins[:1], [out], out))
        elif op == "dot":
            nodes.append(P.node("MatMul", ins, [out], out))
        elif op == "reshape":
            shp = onp.asarray(kw.get("shape"), "int64")
            sname = fresh("shape")
            extra_inits[sname] = shp
            nodes.append(P.node("Reshape", [ins[0], sname], [out], out))
        elif op == "transpose":
            axes = kw.get("axes")
            attrs = [P.attr_ints("perm", axes)] if axes else []
            nodes.append(P.node("Transpose", ins, [out], out, attrs))
        elif op == "clip":
            lo = onp.asarray(kw.get("a_min"), "float32")
            hi = onp.asarray(kw.get("a_max"), "float32")
            ln, hn = fresh("clip_min"), fresh("clip_max")
            extra_inits[ln] = lo
            extra_inits[hn] = hi
            nodes.append(P.node("Clip", [ins[0], ln, hn], [out], out))
        elif op == "Embedding":
            # mx input order (indices, weight) -> Gather(weight, indices);
            # indices cast to int64 (the sym-level dtype is unconstrained)
            idx = fresh("emb_idx")
            nodes.append(P.node("Cast", [ins[0]], [idx], idx,
                                [P.attr_int("to", P.DT_INT64)]))
            nodes.append(P.node("Gather", [ins[1], idx], [out], out,
                                [P.attr_int("axis", 0)]))
        elif op == "LayerNorm":
            nodes.append(P.node("LayerNormalization", ins[:3], [out], out,
                                [P.attr_int("axis", kw.get("axis", -1)),
                                 P.attr_float("epsilon",
                                              kw.get("eps", 1e-5))]))
        elif op == "batch_dot":
            a, b = ins
            # the reference's batch_dot op contract is rank-3 (one batch
            # axis — src/operator/tensor/dot-inl.h), so the transpose
            # fix-up perm is the rank-3 (0, 2, 1)
            if kw.get("transpose_a"):
                t = fresh("bdot_ta")
                nodes.append(P.node("Transpose", [a], [t], t,
                                    [P.attr_ints("perm", (0, 2, 1))]))
                a = t
            if kw.get("transpose_b"):
                t = fresh("bdot_tb")
                nodes.append(P.node("Transpose", [b], [t], t,
                                    [P.attr_ints("perm", (0, 2, 1))]))
                b = t
            nodes.append(P.node("MatMul", [a, b], [out], out))
        elif op == "LeakyReLU":
            at = kw.get("act_type", "leaky")
            if at == "leaky":
                nodes.append(P.node("LeakyRelu", ins[:1], [out], out,
                                    [P.attr_float("alpha",
                                                  kw.get("slope", 0.25))]))
            elif at == "elu":
                nodes.append(P.node("Elu", ins[:1], [out], out,
                                    [P.attr_float("alpha",
                                                  kw.get("slope", 0.25))]))
            elif at == "selu":
                nodes.append(P.node("Selu", ins[:1], [out], out))
            elif at == "prelu":
                nodes.append(P.node("PRelu", ins[:2], [out], out))
            elif at == "gelu":
                # exact gelu = 0.5 * x * (1 + erf(x / sqrt(2))): Erf exists
                # at opset 13, Gelu only at 20
                s = fresh("gelu_s")
                extra_inits[s] = onp.asarray(1.0 / onp.sqrt(2.0), "float32")
                h = fresh("gelu_h")
                extra_inits[h] = onp.asarray(0.5, "float32")
                one = fresh("gelu_1")
                extra_inits[one] = onp.asarray(1.0, "float32")
                d, e, a1, m1 = (fresh("gelu_div"), fresh("gelu_erf"),
                                fresh("gelu_add"), fresh("gelu_mul"))
                nodes.append(P.node("Mul", [ins[0], s], [d], d))
                nodes.append(P.node("Erf", [d], [e], e))
                nodes.append(P.node("Add", [e, one], [a1], a1))
                nodes.append(P.node("Mul", [ins[0], a1], [m1], m1))
                nodes.append(P.node("Mul", [m1, h], [out], out))
            else:
                raise NotImplementedError(
                    "ONNX export: LeakyReLU act_type %r" % at)
        elif op == "where":
            # ONNX Where requires a bool condition; mx conditions are
            # arithmetic 0/1 masks
            cond = fresh("where_cond")
            nodes.append(P.node("Cast", [ins[0]], [cond], cond,
                                [P.attr_int("to", P.DT_BOOL)]))
            nodes.append(P.node("Where", [cond, ins[1], ins[2]], [out], out))
        elif op in _CMP_SCALAR or op in _CMP:
            if op in _CMP:
                o, pair = _CMP[op], ins
            else:
                o = _CMP_SCALAR[op]
                if o is None:
                    raise NotImplementedError("ONNX export: %s" % op)
                sc = fresh("cmp_scalar")
                extra_inits[sc] = onp.asarray(kw["scalar"], "float32")
                pair = [sc, ins[0]] if kw.get("reverse") else [ins[0], sc]
            cb = fresh("cmp_bool")
            nodes.append(P.node(o, pair, [cb], cb))
            nodes.append(P.node("Cast", [cb], [out], out,
                                [P.attr_int("to", P.DT_FLOAT)]))
        elif op == "square":
            nodes.append(P.node("Mul", [ins[0], ins[0]], [out], out))
        elif op == "expand_dims":
            ax = fresh("unsq_axes")
            extra_inits[ax] = onp.asarray([kw["axis"]], "int64")
            nodes.append(P.node("Unsqueeze", [ins[0], ax], [out], out))
        elif op == "squeeze":
            axis = kw.get("axis")
            sq_in = [ins[0]]
            if axis is not None:
                ax = fresh("sq_axes")
                axes = axis if isinstance(axis, (tuple, list)) else (axis,)
                extra_inits[ax] = onp.asarray(axes, "int64")
                sq_in.append(ax)
            nodes.append(P.node("Squeeze", sq_in, [out], out))
        elif op in ("slice_axis", "slice"):
            if op == "slice_axis":
                axes = (kw["axis"],)
                begin = (kw.get("begin") or 0,)
                end = (kw.get("end"),)
                step = (1,)
            else:
                begin = tuple(kw.get("begin") or ())
                end = tuple(kw.get("end") or ())
                step = tuple(kw.get("step") or (1,) * len(begin))
                axes = tuple(range(len(begin)))
            INT_MAX = 2 ** 62
            sp = onp.asarray([s if s is not None else 1 for s in step],
                             "int64")
            if any(s == 0 for s in sp):
                raise ValueError("ONNX export: slice step 0")
            # open (None) bounds follow the step's direction: a negative
            # step starts from the far end (runtimes clamp INT_MAX to
            # dim-1) and runs to before the beginning (-INT_MAX) — the
            # former unconditional +INT_MAX end made conformant runtimes
            # (onnxruntime) evaluate reversed slices as empty
            st = onp.asarray([b if b is not None
                              else (0 if s > 0 else INT_MAX)
                              for b, s in zip(begin, sp)], "int64")
            en = onp.asarray([e if e is not None
                              else (INT_MAX if s > 0 else -INT_MAX)
                              for e, s in zip(end, sp)], "int64")
            sn, enn, axn, spn = (fresh("sl_st"), fresh("sl_en"),
                                 fresh("sl_ax"), fresh("sl_sp"))
            extra_inits[sn] = st
            extra_inits[enn] = en
            extra_inits[axn] = onp.asarray(axes, "int64")
            extra_inits[spn] = sp
            nodes.append(P.node("Slice", [ins[0], sn, enn, axn, spn],
                                [out], out))
        elif op in ("cast", "Cast"):
            nodes.append(P.node(
                "Cast", ins, [out], out,
                [P.attr_int("to", P._NP2ONNX[str(onp.dtype(kw["dtype"]))])]))
        elif op in _SCALAR:
            sc = fresh("scalar")
            extra_inits[sc] = onp.asarray(kw["scalar"], "float32")
            pair = [sc, ins[0]] if kw.get("reverse") else [ins[0], sc]
            nodes.append(P.node(_SCALAR[op], pair, [out], out))
        elif op == "RNN":
            # pops the flat parameter vector from params (it is re-emitted
            # as per-layer W/R/B initializers)
            _export_rnn(base, ins, kw, params, nodes, extra_inits,
                        fresh, out)
        elif op in _ELEM:
            nodes.append(P.node(_ELEM[op], ins, [out], out))
        elif op in _UNARY:
            attrs = [P.attr_int("axis", 1)] if _UNARY[op] == "Flatten" else []
            nodes.append(P.node(_UNARY[op], ins, [out], out, attrs))
        else:
            raise NotImplementedError(
                "ONNX export: unsupported op %r (supported: see module "
                "docstring)" % op)
        name_of[id(base)] = out
        return out

    out_name = emit(sym)

    for ones_name, gamma_name in fix_gamma_ones:
        shp = params[gamma_name].shape if gamma_name in params else (1,)
        extra_inits[ones_name] = onp.ones(shp, "float32")
    for k, v in params.items():
        arr = v.asnumpy() if isinstance(v, NDArray) else onp.asarray(v)
        initializers.append(P.tensor(k, arr))
    for k, v in extra_inits.items():
        initializers.append(P.tensor(k, v))

    inputs = [P.value_info(n, s, input_type)
              for n, s in zip(data_names, input_shape)]
    # ONNX requires initializers to also appear as graph inputs pre-IR4 —
    # modern runtimes don't; we list only real data inputs (IR 8)
    all_shapes = {n: s for n, s in zip(data_names, input_shape)}
    all_shapes.update(shape_hints)
    try:
        _, out_shapes, _ = sym.infer_shape(**all_shapes)
    except Exception:
        out_shapes = None
    outputs = [P.value_info(out_name, out_shapes[0] if out_shapes else (),
                            "float32")]
    g = P.graph("mxtpu_graph", nodes, inputs, outputs, initializers)
    buf = P.model(g, opset=17)   # 17: LayerNormalization
    with open(onnx_file_path, "wb") as f:
        f.write(buf)
    return onnx_file_path


def get_model_metadata(model_file):
    """ref onnx2mx get_model_metadata."""
    with open(model_file, "rb") as f:
        m = P.read_model(f.read())
    g = m["graph"]
    return {"input_tensor_data": P.read_value_infos(g, 11),
            "output_tensor_data": P.read_value_infos(g, 12)}


def import_model(model_file):
    """.onnx file → (sym, arg_params, aux_params) (ref onnx2mx/import_model)."""
    from .. import symbol as mxsym
    from .. import ndarray as nd

    with open(model_file, "rb") as f:
        m = P.read_model(f.read())
    g = m["graph"]
    inits = P.read_initializers(g)
    value = {}  # onnx value name -> Symbol
    for name, _shape, _dt in P.read_value_infos(g, 11):
        value[name] = mxsym.var(name)

    arg_params, aux_params = {}, {}
    for k, v in inits.items():
        arg_params[k] = nd.array(onp.asarray(v))

    def sym_of(name):
        if name in value:
            return value[name]
        if name in inits:
            value[name] = mxsym.var(name)
            return value[name]
        raise ValueError("ONNX import: undefined input %r" % name)

    last = None
    for n in P.read_nodes(g):
        op, at = n["op_type"], n["attrs"]
        if op in ("LSTM", "GRU", "RNN"):
            # "" marks an omitted optional input (sequence_lens, B, h0, c0)
            ins = [sym_of(i) if i else None for i in n["inputs"]]
        else:
            ins = [sym_of(i) for i in n["inputs"]]
        if op == "Gemm":
            if at.get("alpha", 1.0) != 1.0 or at.get("beta", 1.0) != 1.0 \
                    or at.get("transA", 0):
                raise NotImplementedError(
                    "ONNX import: Gemm with alpha/beta != 1 or transA")
            wname = n["inputs"][1]
            if wname not in arg_params:
                raise NotImplementedError(
                    "ONNX import: Gemm weight must be an initializer")
            if not at.get("transB", 0):
                # (in, out) layout → FullyConnected's (out, in)
                arg_params[wname] = nd.array(arg_params[wname].asnumpy().T)
            out = mxsym.FullyConnected(
                data=ins[0], weight=ins[1],
                bias=ins[2] if len(ins) > 2 else None,
                num_hidden=int(arg_params[wname].shape[0]),
                no_bias=len(ins) < 3, flatten=False, name=n["outputs"][0])
        elif op == "Conv":
            w = arg_params[n["inputs"][1]]
            out = mxsym.Convolution(
                data=ins[0], weight=ins[1],
                bias=ins[2] if len(ins) > 2 else None,
                kernel=tuple(at["kernel_shape"]),
                stride=tuple(at.get("strides", (1, 1))),
                pad=_sym_pads(at, len(at["kernel_shape"])),
                dilate=tuple(at.get("dilations", (1, 1))),
                num_filter=int(w.shape[0]),
                num_group=int(at.get("group", 1)),
                no_bias=len(ins) < 3, name=n["outputs"][0])
        elif op == "BatchNormalization":
            # fix_gamma=False: ONNX scale is ALWAYS applied (our export emits
            # explicit ones when the source had fix_gamma=True)
            out = mxsym.BatchNorm(
                data=ins[0], gamma=ins[1], beta=ins[2], moving_mean=ins[3],
                moving_var=ins[4], eps=float(at.get("epsilon", 1e-5)),
                momentum=float(at.get("momentum", 0.9)), fix_gamma=False,
                use_global_stats=True, name=n["outputs"][0])
            for mi, which in ((3, aux_params), (4, aux_params)):
                nm = n["inputs"][mi]
                if nm in arg_params:
                    which[nm] = arg_params.pop(nm)
        elif op == "Softplus":
            out = mxsym.Activation(ins[0], act_type="softrelu")
        elif op in ("Relu", "Sigmoid", "Tanh", "Exp", "Log",
                    "Sqrt", "Neg", "Abs", "Identity"):
            fn = {"Relu": mxsym.relu, "Sigmoid": mxsym.sigmoid,
                  "Tanh": mxsym.tanh, "Exp": mxsym.exp, "Log": mxsym.log,
                  "Sqrt": mxsym.sqrt, "Neg": mxsym.negative,
                  "Abs": mxsym.abs, "Identity": mxsym.identity}[op]
            out = fn(ins[0])
        elif op in ("MaxPool", "AveragePool"):
            out = mxsym.Pooling(
                data=ins[0], kernel=tuple(at["kernel_shape"]),
                stride=tuple(at.get("strides", (1, 1))),
                pad=_sym_pads(at, len(at["kernel_shape"])),
                pool_type="max" if op == "MaxPool" else "avg",
                name=n["outputs"][0])
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            out = mxsym.Pooling(
                data=ins[0], global_pool=True,
                pool_type="max" if op == "GlobalMaxPool" else "avg",
                kernel=(1, 1), name=n["outputs"][0])
        elif op == "Flatten":
            out = mxsym.flatten(ins[0])
        elif op == "Softmax":
            out = mxsym.softmax(ins[0], axis=int(at.get("axis", -1)))
        elif op == "Dropout":
            out = mxsym.identity(ins[0])
        elif op == "Concat":
            out = mxsym.concat(*ins, dim=int(at.get("axis", 1)))
        elif op == "MatMul":
            # ONNX MatMul is numpy-matmul (batched on leading dims) —
            # linalg_gemm2, not the 2-D-only dot
            out = mxsym.linalg_gemm2(ins[0], ins[1])
        elif op == "Reshape":
            shp = tuple(int(x) for x in
                        onp.asarray(inits[n["inputs"][1]]).tolist())
            arg_params.pop(n["inputs"][1], None)
            out = mxsym.reshape(ins[0], shape=shp)
        elif op == "Transpose":
            out = mxsym.transpose(ins[0], axes=tuple(at["perm"])
                                  if "perm" in at else None)
        elif op == "Clip":
            lo = float(onp.asarray(inits[n["inputs"][1]]))
            hi = float(onp.asarray(inits[n["inputs"][2]]))
            arg_params.pop(n["inputs"][1], None)
            arg_params.pop(n["inputs"][2], None)
            out = mxsym.clip(ins[0], a_min=lo, a_max=hi)
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": mxsym.broadcast_add, "Sub": mxsym.broadcast_sub,
                  "Mul": mxsym.broadcast_mul, "Div": mxsym.broadcast_div}[op]
            out = fn(ins[0], ins[1])
        elif op == "Pow":
            out = mxsym.broadcast_power(ins[0], ins[1])
        elif op in ("Max", "Min"):
            fn = (mxsym.broadcast_maximum if op == "Max"
                  else mxsym.broadcast_minimum)
            out = ins[0]
            for other in ins[1:]:
                out = fn(out, other)
        elif op in ("Greater", "Less", "GreaterOrEqual", "LessOrEqual",
                    "Equal"):
            fn = {"Greater": mxsym.broadcast_greater,
                  "Less": mxsym.broadcast_lesser,
                  "GreaterOrEqual": mxsym.broadcast_greater_equal,
                  "LessOrEqual": mxsym.broadcast_lesser_equal,
                  "Equal": mxsym.broadcast_equal}[op]
            out = fn(ins[0], ins[1])
        elif op == "Erf":
            out = mxsym.erf(ins[0])
        elif op == "Where":
            out = mxsym.where(ins[0], ins[1], ins[2])
        elif op == "Gather":
            out = mxsym.take(ins[0], ins[1], axis=int(at.get("axis", 0)))
        elif op == "Cast":
            out = mxsym.cast(ins[0], dtype=P._ONNX2NP[int(at["to"])])
        elif op == "LayerNormalization":
            out = mxsym.LayerNorm(ins[0], ins[1], ins[2],
                                  axis=int(at.get("axis", -1)),
                                  eps=float(at.get("epsilon", 1e-5)))
        elif op == "LeakyRelu":
            out = mxsym.LeakyReLU(ins[0], act_type="leaky",
                                  slope=float(at.get("alpha", 0.01)))
        elif op == "Elu":
            out = mxsym.LeakyReLU(ins[0], act_type="elu",
                                  slope=float(at.get("alpha", 1.0)))
        elif op == "Selu":
            out = mxsym.LeakyReLU(ins[0], act_type="selu")
        elif op == "PRelu":
            out = mxsym.LeakyReLU(ins[0], gamma=ins[1], act_type="prelu")
        elif op == "Unsqueeze":
            axes = [int(a) for a in onp.asarray(inits[n["inputs"][1]])]
            arg_params.pop(n["inputs"][1], None)
            # axes reference positions in the OUTPUT rank: non-negative
            # axes apply ascending, all-negative apply descending (each
            # expand_dims(-k) then lands at its final position); a mix
            # cannot be resolved without the input rank
            if all(a >= 0 for a in axes):
                order = sorted(axes)
            elif all(a < 0 for a in axes):
                order = sorted(axes, reverse=True)
            else:
                raise NotImplementedError(
                    "ONNX import: Unsqueeze with mixed-sign axes %r" % axes)
            out = ins[0]
            for a in order:
                out = mxsym.expand_dims(out, axis=a)
        elif op == "Squeeze":
            if len(n["inputs"]) > 1 and n["inputs"][1]:
                axes = tuple(int(a)
                             for a in onp.asarray(inits[n["inputs"][1]]))
                arg_params.pop(n["inputs"][1], None)
                out = mxsym.squeeze(ins[0], axis=axes if len(axes) > 1
                                    else axes[0])
            else:
                out = mxsym.squeeze(ins[0])
        elif op == "Slice":
            names = n["inputs"]
            starts = [int(v) for v in onp.asarray(inits[names[1]])]
            ends = [int(v) for v in onp.asarray(inits[names[2]])]
            axes = ([int(v) for v in onp.asarray(inits[names[3]])]
                    if len(names) > 3 and names[3]
                    else list(range(len(starts))))
            steps = ([int(v) for v in onp.asarray(inits[names[4]])]
                     if len(names) > 4 and names[4] else [1] * len(starts))
            if any(s < 1 for s in steps):
                raise NotImplementedError("ONNX import: Slice steps < 1")
            for nm in names[1:]:
                arg_params.pop(nm, None)
            INT_MAX = 2 ** 62
            if all(s == 1 for s in steps):
                out = ins[0]
                for ax, b, e in zip(axes, starts, ends):
                    out = mxsym.slice_axis(out, axis=ax, begin=b,
                                           end=None if e >= INT_MAX else e)
            else:
                # strided slice: mx.sym.slice takes per-leading-axis
                # begin/end/step tuples, so axes must be non-negative —
                # a raw -1 would compute rank 0 and mis-index; the input
                # rank is not known symbolically here, so reject loudly
                # (the unit-step slice_axis path above tolerates them)
                if any(a < 0 for a in axes):
                    raise NotImplementedError(
                        "ONNX import: strided Slice with negative axes %r "
                        "(input rank unknown at import)" % (axes,))
                rank = max(axes) + 1
                bg, en_, sp = ([0] * rank, [None] * rank, [1] * rank)
                for ax, b, e, s in zip(axes, starts, ends, steps):
                    bg[ax] = b
                    en_[ax] = None if e >= INT_MAX else e
                    sp[ax] = s
                out = mxsym.slice(ins[0], begin=tuple(bg), end=tuple(en_),
                                  step=tuple(sp))
        elif op in ("LSTM", "GRU", "RNN"):
            out = _import_rnn(n, at, ins, inits, arg_params, value,
                              mxsym, nd, op)
            # only Y maps; binding Y_h/Y_c to the same tensor would
            # silently hand consumers the full sequence — leave them
            # unbound so sym_of fails loudly instead
            value[n["outputs"][0]] = out
            last = out
            continue
        else:
            raise NotImplementedError("ONNX import: unsupported op %r" % op)
        for o in n["outputs"]:
            value[o] = out
        last = out
    # the graph's DECLARED outputs win over file order (field 12)
    declared = [name for name, _s, _d in P.read_value_infos(g, 12)]
    if declared:
        if declared[0] not in value:
            # e.g. an RNN Y_h/Y_c consumer: falling back to the last node
            # would silently return the wrong tensor
            raise ValueError("ONNX import: undefined input %r (declared "
                             "graph output was never produced)"
                             % declared[0])
        last = value[declared[0]]
    return last, arg_params, aux_params


def _import_rnn(n, at, ins, inits, arg_params, value, mxsym, nd, op):
    """ONNX LSTM/GRU/RNN node -> sym.RNN: per-direction W/R/B initializers
    repacked (ONNX gate order -> our cuDNN layout) into the flat parameter
    vector; an omitted initial state maps to nd.RNN's state=None zeros."""
    H = int(at["hidden_size"])
    bidir = at.get("direction", "forward") == "bidirectional"
    D = 2 if bidir else 1
    if at.get("clip") or at.get("layout"):
        raise NotImplementedError("ONNX import: RNN clip/layout attrs")
    acts = at.get("activations")
    mode = {"LSTM": "lstm", "GRU": "gru"}.get(op)
    if mode is None:
        # vanilla RNN: one activation per direction, all equal
        acts = acts or ["Tanh"] * D
        if len(set(acts)) != 1 or acts[0] not in ("Relu", "Tanh"):
            raise NotImplementedError(
                "ONNX import: RNN activations %r (need uniform Relu or "
                "Tanh)" % (acts,))
        mode = "rnn_relu" if acts[0] == "Relu" else "rnn_tanh"
    elif acts is not None:
        # sym.RNN's recurrence is the cuDNN fixed set — anything else
        # would silently change numerics
        default = (["Sigmoid", "Tanh", "Tanh"] if op == "LSTM"
                   else ["Sigmoid", "Tanh"]) * D
        if list(acts) != default:
            raise NotImplementedError(
                "ONNX import: non-default %s activations %r" % (op, acts))
    G = {"lstm": 4, "gru": 3}.get(mode, 1)
    names = n["inputs"]
    if len(names) > 4 and names[4]:
        raise NotImplementedError("ONNX import: RNN sequence_lens")
    if op == "GRU" and not int(at.get("linear_before_reset", 0)):
        raise NotImplementedError(
            "ONNX import: GRU linear_before_reset=0 (cuDNN layout is 1)")
    # read_initializers yields plain numpy arrays — one uniform conversion
    # for W, R, and B (no wrapper special-cases)
    W = onp.asarray(inits[names[1]], "float32")
    R = onp.asarray(inits[names[2]], "float32")
    B = (onp.asarray(inits[names[3]], "float32")
         if len(names) > 3 and names[3]
         else onp.zeros((D, 2 * G * H), "float32"))
    for nm in names[1:4]:
        if nm:
            arg_params.pop(nm, None)
    wi = [_gate_reorder(W[d], mode, inverse=True) for d in range(D)]
    wh = [_gate_reorder(R[d], mode, inverse=True) for d in range(D)]
    bi = [_gate_reorder(B[d][:G * H], mode, inverse=True) for d in range(D)]
    bh = [_gate_reorder(B[d][G * H:], mode, inverse=True) for d in range(D)]
    flat = onp.concatenate(
        [x.ravel() for pair in zip(wi, wh) for x in pair]
        + [x.ravel() for pair in zip(bi, bh) for x in pair])
    pname = (n["name"] or n["outputs"][0]) + "_parameters"
    arg_params[pname] = nd.array(flat)
    value[pname] = mxsym.var(pname)
    h0 = ins[5] if len(ins) > 5 else None
    c0 = ins[6] if mode == "lstm" and len(ins) > 6 else None
    rnn_out = mxsym.RNN(ins[0], value[pname], h0, c0, state_size=H,
                        num_layers=1, mode=mode, bidirectional=bidir)
    # our (T, N, D*H) -> ONNX Y layout (T, D, N, H); only Y is mapped —
    # a graph consuming Y_h/Y_c fails loudly at sym_of
    return mxsym.transpose(mxsym.reshape(rnn_out, shape=(0, 0, D, -1)),
                           axes=(0, 2, 1, 3))


def _gate_reorder(a, mode, inverse=False):
    """Permute the G gate blocks along axis 0 of a (G*H, ...) weight/bias
    between our cuDNN layout and ONNX's (see _GATE_PERM)."""
    perm = (_GATE_UNPERM if inverse else _GATE_PERM)[mode]
    parts = onp.split(a, len(perm), axis=0)
    return onp.concatenate([parts[p] for p in perm], axis=0)


def _export_rnn(base, ins, kw, params, nodes, extra_inits, fresh, out):
    """Monolithic RNN op -> a stack of ONNX LSTM/GRU/RNN nodes (one per
    layer), unpacking the flat cuDNN-layout parameter vector
    (ndarray/rnn_op.py _dims) into per-layer W/R/B initializers with the
    gate blocks repacked to ONNX order."""
    from ..ndarray import NDArray
    from ..ndarray.rnn_op import _dims

    mode = kw.get("mode", "lstm")
    H = int(kw["state_size"])
    L = int(kw.get("num_layers", 1))
    bidir = bool(kw.get("bidirectional", False))
    D = 2 if bidir else 1
    if kw.get("state_outputs"):
        raise NotImplementedError("ONNX export: RNN state_outputs=True")
    # resolve the POSITIONAL slots (data, parameters, state, state_cell):
    # an omitted optional input is an "N" entry in __arg_spec__ with NO
    # corresponding element in ins/_inputs, so raw positions shift —
    # e.g. RNN(data, p, None, c0) has c0 at ins[2], not ins[3]
    spec = kw.get("__arg_spec__")
    slot_names, slot_syms = [], []
    ii = 0
    for s in (spec or (None,) * len(ins)):
        if s == "N":
            slot_names.append(None)
            slot_syms.append(None)
        elif s is None:
            slot_names.append(ins[ii])
            slot_syms.append(base._inputs[ii])
            ii += 1
        else:
            raise NotImplementedError("ONNX export: RNN list inputs")
    psym = slot_syms[1] if len(slot_syms) > 1 else None
    pbase = psym and (getattr(psym, "_base", None) or psym)
    if not (pbase is not None and pbase.is_var and pbase.name in params):
        raise NotImplementedError(
            "ONNX export: the RNN parameter vector must be an initializer")
    flat = params.pop(pbase.name)
    flat = flat.asnumpy() if isinstance(flat, NDArray) else onp.asarray(flat)
    G = {"lstm": 4, "gru": 3}.get(mode, 1)
    # input size from the flat length: total = D*G*H*(I+H) [layer 0]
    #   + (L-1)*D*G*H*(D*H+H) [stacked layers] + L*D*2*G*H [biases]
    rest = flat.size - L * D * 2 * G * H - (L - 1) * D * G * H * (D * H + H)
    I = rest // (D * G * H) - H
    blocks, off = {}, 0
    for kind, layer, d, shp in _dims(mode, int(I), H, L, bidir):
        n_el = int(onp.prod(shp))
        blocks[(kind, layer, d)] = flat[off:off + n_el].reshape(shp)
        off += n_el
    if off != flat.size:
        raise ValueError("RNN parameter vector length mismatch")

    x_name = slot_names[0]
    state_name = (slot_names[2] or "") if len(slot_names) > 2 else ""
    cell_name = (slot_names[3] or "") if len(slot_names) > 3 else ""

    def state_slice(src, layer, tag):
        if L == 1:
            return src   # the whole state IS this layer's (D, N, H)
        o = fresh("rnn_%s" % tag)
        sn, en, an = fresh("rnn_st"), fresh("rnn_en"), fresh("rnn_ax")
        extra_inits[sn] = onp.asarray([layer * D], "int64")
        extra_inits[en] = onp.asarray([(layer + 1) * D], "int64")
        extra_inits[an] = onp.asarray([0], "int64")
        nodes.append(P.node("Slice", [src, sn, en, an], [o], o))
        return o

    for layer in range(L):
        W = onp.stack([_gate_reorder(blocks[("wi", layer, d)], mode)
                       for d in range(D)]).astype("float32")
        R = onp.stack([_gate_reorder(blocks[("wh", layer, d)], mode)
                       for d in range(D)]).astype("float32")
        B = onp.stack([onp.concatenate(
            [_gate_reorder(blocks[("bi", layer, d)], mode),
             _gate_reorder(blocks[("bh", layer, d)], mode)])
            for d in range(D)]).astype("float32")
        wn, rn, bn = fresh("rnn_W"), fresh("rnn_R"), fresh("rnn_B")
        extra_inits[wn] = W
        extra_inits[rn] = R
        extra_inits[bn] = B
        node_in = [x_name, wn, rn, bn, ""]   # sequence_lens: absent
        node_in.append(state_slice(state_name, layer, "h0")
                       if state_name else "")
        if mode == "lstm":
            node_in.append(state_slice(cell_name, layer, "c0")
                           if cell_name else "")
        attrs = [P.attr_int("hidden_size", H),
                 P.attr_string("direction",
                               "bidirectional" if bidir else "forward")]
        if mode == "gru":
            # our recurrence applies the reset gate AFTER h's linear map
            # (incl. bias) — exactly ONNX linear_before_reset=1
            attrs.append(P.attr_int("linear_before_reset", 1))
        if mode in ("rnn_relu", "rnn_tanh"):
            act = "Relu" if mode == "rnn_relu" else "Tanh"
            attrs.append(P.attr_strings("activations", [act] * D))
        y = fresh("rnn_Y")
        outs = [y, fresh("rnn_Yh")] + ([fresh("rnn_Yc")]
                                       if mode == "lstm" else [])
        nodes.append(P.node(_ONNX_RNN_OP[mode], node_in, outs, y, attrs))
        # ONNX Y (T, D, N, H) -> our layout (T, N, D*H)
        tr = fresh("rnn_tr")
        nodes.append(P.node("Transpose", [y], [tr], tr,
                            [P.attr_ints("perm", (0, 2, 1, 3))]))
        shp = fresh("rnn_shp")
        extra_inits[shp] = onp.asarray([0, 0, -1], "int64")
        dst = out if layer == L - 1 else fresh("rnn_X")
        nodes.append(P.node("Reshape", [tr, shp], [dst], dst))
        x_name = dst


def _sym_pads(at, ndim):
    """ONNX pads are [begin..., end...]; mx supports symmetric only."""
    pads = tuple(at.get("pads", (0,) * 2 * ndim))
    begin, end = pads[:ndim], pads[ndim:2 * ndim]
    if end and begin != end:
        raise NotImplementedError(
            "ONNX import: asymmetric padding %s unsupported" % (pads,))
    if at.get("auto_pad", "") not in ("", "NOTSET"):
        raise NotImplementedError("ONNX import: auto_pad unsupported")
    return begin
