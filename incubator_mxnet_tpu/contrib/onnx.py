"""ONNX interop (ref python/mxnet/contrib/onnx/ mx2onnx + onnx2mx).

REAL .onnx emission/parsing with no dependency on the `onnx` package (absent
in this image): contrib.onnx_proto implements the protobuf wire format for
the ONNX IR subset used here. Exported files are standard ModelProto
(ir_version 8, opset 13) loadable by onnxruntime/netron; import maps ONNX
nodes back onto mx.sym ops and round-trips numerically (tests/test_onnx.py).

Supported ops (the model-zoo CNN surface): Conv, Gemm (FullyConnected),
BatchNormalization, Relu/Sigmoid/Tanh/Softplus, MaxPool/AveragePool/
GlobalAveragePool/GlobalMaxPool, Flatten, Softmax, Dropout, Concat, Add/Sub/
Mul/Div, MatMul, Exp/Log/Sqrt/Neg/Abs, Reshape, Transpose, Clip.
"""
from __future__ import annotations

import numpy as onp

from . import onnx_proto as P

__all__ = ["export_model", "import_model", "get_model_metadata"]

_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus"}
_ELEM = {"add": "Add", "elemwise_add": "Add", "broadcast_add": "Add",
         "subtract": "Sub", "elemwise_sub": "Sub", "broadcast_sub": "Sub",
         "multiply": "Mul", "elemwise_mul": "Mul", "broadcast_mul": "Mul",
         "divide": "Div", "elemwise_div": "Div", "broadcast_div": "Div",
         "_plus": "Add", "_minus": "Sub", "_mul": "Mul", "_div": "Div"}
_UNARY = {"exp": "Exp", "log": "Log", "sqrt": "Sqrt", "negative": "Neg",
          "abs": "Abs", "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "identity": "Identity", "flatten": "Flatten"}


def export_model(sym, params, input_shape, input_type="float32",
                 onnx_file_path="model.onnx", verbose=False):
    """Symbol + params → .onnx file (ref mx2onnx/export_model.py).

    input_shape: one shape tuple (single data input) or list of tuples
    matching the non-parameter arguments in order.
    """
    from ..ndarray import NDArray

    if isinstance(input_shape, tuple):
        input_shape = [input_shape]
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}

    nodes, initializers, extra_inits = [], [], {}
    fix_gamma_ones = []  # (ones_init_name, gamma_value_name) for BatchNorm
    arg_names = sym.list_arguments()
    data_names = [n for n in arg_names if n not in params]
    if len(data_names) != len(input_shape):
        raise ValueError("input_shape entries (%d) must match data inputs %s"
                         % (len(input_shape), data_names))

    name_of = {}
    counter = [0]

    def fresh(prefix):
        counter[0] += 1
        return "%s_%d" % (prefix, counter[0])

    def emit(s):
        """Returns the output value name for node s."""
        base = getattr(s, "_base", None) or s
        if id(base) in name_of:
            return name_of[id(base)]
        if base.is_var:
            name_of[id(base)] = base.name
            return base.name
        ins = [emit(i) for i in base._inputs]
        op, kw = base._op_name, base._kwargs
        out = base.name
        if op == "FullyConnected":
            a = ins[0]
            if kw.get("flatten", True):
                f = fresh("flat")
                nodes.append(P.node("Flatten", [a], [f], f,
                                    [P.attr_int("axis", 1)]))
                a = f
            attrs = [P.attr_float("alpha", 1.0), P.attr_float("beta", 1.0),
                     P.attr_int("transB", 1)]
            gemm_in = [a, ins[1]] + (ins[2:3] if not kw.get("no_bias") else [])
            nodes.append(P.node("Gemm", gemm_in, [out], out, attrs))
        elif op == "Convolution":
            attrs = [P.attr_ints("kernel_shape", kw["kernel"]),
                     P.attr_ints("strides", kw.get("stride", (1, 1))),
                     P.attr_ints("pads", tuple(kw.get("pad", (0, 0))) * 2),
                     P.attr_ints("dilations", kw.get("dilate", (1, 1))),
                     P.attr_int("group", kw.get("num_group", 1))]
            cin = ins[:2] + (ins[2:3] if not kw.get("no_bias") else [])
            nodes.append(P.node("Conv", cin, [out], out, attrs))
        elif op == "BatchNorm":
            attrs = [P.attr_float("epsilon", kw.get("eps", 1e-5)),
                     P.attr_float("momentum", kw.get("momentum", 0.9))]
            # mx order: data,gamma,beta,mean,var == onnx: X,scale,B,mean,var.
            # fix_gamma=True (mx default) means gamma is IGNORED in compute —
            # ONNX has no such flag, so emit a ones scale to match the math
            if kw.get("fix_gamma", True):
                ones_name = fresh("bn_scale_ones")
                extra_inits[ones_name] = None  # filled after shapes known
                fix_gamma_ones.append((ones_name, ins[1]))
                ins = [ins[0], ones_name] + ins[2:]
            nodes.append(P.node("BatchNormalization", ins[:5], [out], out,
                                attrs))
        elif op == "Activation":
            nodes.append(P.node(_ACT[kw.get("act_type", "relu")], ins, [out],
                                out))
        elif op == "Pooling":
            ptype = kw.get("pool_type", "max")
            if kw.get("global_pool"):
                o = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
                nodes.append(P.node(o, ins, [out], out))
            else:
                o = "MaxPool" if ptype == "max" else "AveragePool"
                k = tuple(kw["kernel"])
                attrs = [P.attr_ints("kernel_shape", k),
                         P.attr_ints("strides", kw.get("stride") or (1,) * len(k)),
                         P.attr_ints("pads",
                                     tuple(kw.get("pad") or (0,) * len(k)) * 2)]
                if o == "AveragePool":
                    attrs.append(P.attr_int("count_include_pad", 1))
                nodes.append(P.node(o, ins, [out], out, attrs))
        elif op in ("softmax", "SoftmaxOutput", "log_softmax"):
            nodes.append(P.node("Softmax", ins[:1], [out], out,
                                [P.attr_int("axis", kw.get("axis", -1))]))
            if op == "log_softmax":
                lg = fresh("log")
                nodes.append(P.node("Log", [out], [lg], lg))
                name_of[id(base)] = lg
                return lg
        elif op == "concat":
            nodes.append(P.node("Concat", ins, [out], out,
                                [P.attr_int("axis", kw.get("dim",
                                                           kw.get("axis", 1)))]))
        elif op == "Dropout":
            nodes.append(P.node("Dropout", ins[:1], [out], out))
        elif op in ("dot", "batch_dot"):
            nodes.append(P.node("MatMul", ins, [out], out))
        elif op == "reshape":
            shp = onp.asarray(kw.get("shape"), "int64")
            sname = fresh("shape")
            extra_inits[sname] = shp
            nodes.append(P.node("Reshape", [ins[0], sname], [out], out))
        elif op == "transpose":
            axes = kw.get("axes")
            attrs = [P.attr_ints("perm", axes)] if axes else []
            nodes.append(P.node("Transpose", ins, [out], out, attrs))
        elif op == "clip":
            lo = onp.asarray(kw.get("a_min"), "float32")
            hi = onp.asarray(kw.get("a_max"), "float32")
            ln, hn = fresh("clip_min"), fresh("clip_max")
            extra_inits[ln] = lo
            extra_inits[hn] = hi
            nodes.append(P.node("Clip", [ins[0], ln, hn], [out], out))
        elif op in _ELEM:
            nodes.append(P.node(_ELEM[op], ins, [out], out))
        elif op in _UNARY:
            attrs = [P.attr_int("axis", 1)] if _UNARY[op] == "Flatten" else []
            nodes.append(P.node(_UNARY[op], ins, [out], out, attrs))
        else:
            raise NotImplementedError(
                "ONNX export: unsupported op %r (supported: see module "
                "docstring)" % op)
        name_of[id(base)] = out
        return out

    out_name = emit(sym)

    for ones_name, gamma_name in fix_gamma_ones:
        shp = params[gamma_name].shape if gamma_name in params else (1,)
        extra_inits[ones_name] = onp.ones(shp, "float32")
    for k, v in params.items():
        arr = v.asnumpy() if isinstance(v, NDArray) else onp.asarray(v)
        initializers.append(P.tensor(k, arr))
    for k, v in extra_inits.items():
        initializers.append(P.tensor(k, v))

    inputs = [P.value_info(n, s, input_type)
              for n, s in zip(data_names, input_shape)]
    # ONNX requires initializers to also appear as graph inputs pre-IR4 —
    # modern runtimes don't; we list only real data inputs (IR 8)
    all_shapes = {n: s for n, s in zip(data_names, input_shape)}
    all_shapes.update({k: tuple(v.shape) for k, v in params.items()})
    try:
        _, out_shapes, _ = sym.infer_shape(**all_shapes)
    except Exception:
        out_shapes = None
    outputs = [P.value_info(out_name, out_shapes[0] if out_shapes else (),
                            "float32")]
    g = P.graph("mxtpu_graph", nodes, inputs, outputs, initializers)
    buf = P.model(g)
    with open(onnx_file_path, "wb") as f:
        f.write(buf)
    return onnx_file_path


def get_model_metadata(model_file):
    """ref onnx2mx get_model_metadata."""
    with open(model_file, "rb") as f:
        m = P.read_model(f.read())
    g = m["graph"]
    return {"input_tensor_data": P.read_value_infos(g, 11),
            "output_tensor_data": P.read_value_infos(g, 12)}


def import_model(model_file):
    """.onnx file → (sym, arg_params, aux_params) (ref onnx2mx/import_model)."""
    from .. import symbol as mxsym
    from .. import ndarray as nd

    with open(model_file, "rb") as f:
        m = P.read_model(f.read())
    g = m["graph"]
    inits = P.read_initializers(g)
    value = {}  # onnx value name -> Symbol
    for name, _shape, _dt in P.read_value_infos(g, 11):
        value[name] = mxsym.var(name)

    arg_params, aux_params = {}, {}
    for k, v in inits.items():
        arg_params[k] = nd.array(onp.asarray(v))

    def sym_of(name):
        if name in value:
            return value[name]
        if name in inits:
            value[name] = mxsym.var(name)
            return value[name]
        raise ValueError("ONNX import: undefined input %r" % name)

    last = None
    for n in P.read_nodes(g):
        ins = [sym_of(i) for i in n["inputs"]]
        op, at = n["op_type"], n["attrs"]
        if op == "Gemm":
            if at.get("alpha", 1.0) != 1.0 or at.get("beta", 1.0) != 1.0 \
                    or at.get("transA", 0):
                raise NotImplementedError(
                    "ONNX import: Gemm with alpha/beta != 1 or transA")
            wname = n["inputs"][1]
            if wname not in arg_params:
                raise NotImplementedError(
                    "ONNX import: Gemm weight must be an initializer")
            if not at.get("transB", 0):
                # (in, out) layout → FullyConnected's (out, in)
                arg_params[wname] = nd.array(arg_params[wname].asnumpy().T)
            out = mxsym.FullyConnected(
                data=ins[0], weight=ins[1],
                bias=ins[2] if len(ins) > 2 else None,
                num_hidden=int(arg_params[wname].shape[0]),
                no_bias=len(ins) < 3, flatten=False, name=n["outputs"][0])
        elif op == "Conv":
            w = arg_params[n["inputs"][1]]
            out = mxsym.Convolution(
                data=ins[0], weight=ins[1],
                bias=ins[2] if len(ins) > 2 else None,
                kernel=tuple(at["kernel_shape"]),
                stride=tuple(at.get("strides", (1, 1))),
                pad=_sym_pads(at, len(at["kernel_shape"])),
                dilate=tuple(at.get("dilations", (1, 1))),
                num_filter=int(w.shape[0]),
                num_group=int(at.get("group", 1)),
                no_bias=len(ins) < 3, name=n["outputs"][0])
        elif op == "BatchNormalization":
            # fix_gamma=False: ONNX scale is ALWAYS applied (our export emits
            # explicit ones when the source had fix_gamma=True)
            out = mxsym.BatchNorm(
                data=ins[0], gamma=ins[1], beta=ins[2], moving_mean=ins[3],
                moving_var=ins[4], eps=float(at.get("epsilon", 1e-5)),
                momentum=float(at.get("momentum", 0.9)), fix_gamma=False,
                use_global_stats=True, name=n["outputs"][0])
            for mi, which in ((3, aux_params), (4, aux_params)):
                nm = n["inputs"][mi]
                if nm in arg_params:
                    which[nm] = arg_params.pop(nm)
        elif op == "Softplus":
            out = mxsym.Activation(ins[0], act_type="softrelu")
        elif op in ("Relu", "Sigmoid", "Tanh", "Exp", "Log",
                    "Sqrt", "Neg", "Abs", "Identity"):
            fn = {"Relu": mxsym.relu, "Sigmoid": mxsym.sigmoid,
                  "Tanh": mxsym.tanh, "Exp": mxsym.exp, "Log": mxsym.log,
                  "Sqrt": mxsym.sqrt, "Neg": mxsym.negative,
                  "Abs": mxsym.abs, "Identity": mxsym.identity}[op]
            out = fn(ins[0])
        elif op in ("MaxPool", "AveragePool"):
            out = mxsym.Pooling(
                data=ins[0], kernel=tuple(at["kernel_shape"]),
                stride=tuple(at.get("strides", (1, 1))),
                pad=_sym_pads(at, len(at["kernel_shape"])),
                pool_type="max" if op == "MaxPool" else "avg",
                name=n["outputs"][0])
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            out = mxsym.Pooling(
                data=ins[0], global_pool=True,
                pool_type="max" if op == "GlobalMaxPool" else "avg",
                kernel=(1, 1), name=n["outputs"][0])
        elif op == "Flatten":
            out = mxsym.flatten(ins[0])
        elif op == "Softmax":
            out = mxsym.softmax(ins[0], axis=int(at.get("axis", -1)))
        elif op == "Dropout":
            out = mxsym.identity(ins[0])
        elif op == "Concat":
            out = mxsym.concat(*ins, dim=int(at.get("axis", 1)))
        elif op == "MatMul":
            out = mxsym.dot(ins[0], ins[1])
        elif op == "Reshape":
            shp = tuple(int(x) for x in
                        onp.asarray(inits[n["inputs"][1]]).tolist())
            arg_params.pop(n["inputs"][1], None)
            out = mxsym.reshape(ins[0], shape=shp)
        elif op == "Transpose":
            out = mxsym.transpose(ins[0], axes=tuple(at["perm"])
                                  if "perm" in at else None)
        elif op == "Clip":
            lo = float(onp.asarray(inits[n["inputs"][1]]))
            hi = float(onp.asarray(inits[n["inputs"][2]]))
            arg_params.pop(n["inputs"][1], None)
            arg_params.pop(n["inputs"][2], None)
            out = mxsym.clip(ins[0], a_min=lo, a_max=hi)
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": mxsym.broadcast_add, "Sub": mxsym.broadcast_sub,
                  "Mul": mxsym.broadcast_mul, "Div": mxsym.broadcast_div}[op]
            out = fn(ins[0], ins[1])
        else:
            raise NotImplementedError("ONNX import: unsupported op %r" % op)
        for o in n["outputs"]:
            value[o] = out
        last = out
    # the graph's DECLARED outputs win over file order (field 12)
    declared = [name for name, _s, _d in P.read_value_infos(g, 12)]
    if declared and declared[0] in value:
        last = value[declared[0]]
    return last, arg_params, aux_params


def _sym_pads(at, ndim):
    """ONNX pads are [begin..., end...]; mx supports symmetric only."""
    pads = tuple(at.get("pads", (0,) * 2 * ndim))
    begin, end = pads[:ndim], pads[ndim:2 * ndim]
    if end and begin != end:
        raise NotImplementedError(
            "ONNX import: asymmetric padding %s unsupported" % (pads,))
    if at.get("auto_pad", "") not in ("", "NOTSET"):
        raise NotImplementedError("ONNX import: auto_pad unsupported")
    return begin
