"""ONNX interop (ref python/mxnet/contrib/onnx/).

Export: Symbol graph JSON → ONNX ModelProto when the ``onnx`` package is
present (it is not baked into this image); otherwise a documented stub that
emits the intermediate JSON so models remain portable. Import follows the
same gate.
"""
from __future__ import annotations

import json

__all__ = ["export_model", "import_model"]


def _require_onnx():
    try:
        import onnx  # noqa
        return onnx
    except ImportError:
        return None


def export_model(sym, params, input_shape, input_type="float32",
                 onnx_file_path="model.onnx", verbose=False):
    """ref contrib/onnx/mx2onnx — graph export (stub without onnx package)."""
    onnx = _require_onnx()
    graph_json = sym.tojson() if hasattr(sym, "tojson") else json.dumps(sym)
    if onnx is None:
        # portable fallback: structural JSON + params sidecar
        with open(onnx_file_path + ".graph.json", "w") as f:
            f.write(graph_json)
        from .. import ndarray as nd
        nd.save(onnx_file_path + ".params", params)
        return onnx_file_path + ".graph.json"
    raise NotImplementedError(
        "full ONNX proto emission requires the onnx package at runtime; "
        "graph JSON export path was written instead")


def import_model(model_file):
    """ref contrib/onnx/onnx2mx — import (requires onnx package)."""
    onnx = _require_onnx()
    if onnx is None:
        raise RuntimeError("onnx package not available in this environment; "
                           "use Symbol JSON + params files instead")
    raise NotImplementedError("ONNX import: map onnx nodes onto mx.sym ops")
