"""mx.contrib (ref python/mxnet/contrib/__init__.py)."""
from . import amp  # noqa
from . import quantization  # noqa
from . import tensorboard  # noqa
from . import onnx  # noqa
from . import serving  # noqa
from . import text  # noqa
from . import svrg  # noqa
