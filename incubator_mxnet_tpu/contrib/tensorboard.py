"""TensorBoard logging hook (ref python/mxnet/contrib/tensorboard.py).

Writes scalar summaries via tensorboardX/tensorboard if installed, else
falls back to a JSONL event log readable by any dashboard. The JSONL
schema is STABLE: one ``{"ts": <epoch s>, "step": <int>, "name": <str>,
"value": <float>}`` object per line — a fixed shape any consumer can
parse without knowing the metric names in advance (the old
``{ts, step, <name>: value}`` dynamic-key form forced schema inference
per line).

Own the handle: call ``close()`` (or use the callback as a context
manager) so the last buffered lines hit disk deterministically — relying
on interpreter teardown to flush a half-written epoch is how metric
tails go missing.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        os.makedirs(logging_dir, exist_ok=True)
        self._writer = None
        self._jsonl = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # torch is baked in
            self._writer = SummaryWriter(logging_dir)
        except Exception:
            self._jsonl = open(os.path.join(logging_dir, "metrics.jsonl"), "a")
        self.step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        if self._writer is None and self._jsonl is None:
            raise ValueError("LogMetricsCallback is closed")
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            if self._writer is not None:
                self._writer.add_scalar(name, value, self.step)
            else:
                # stable fixed-key schema: ts is a wall-clock TIMESTAMP
                # (never differenced), name/value are explicit fields
                self._jsonl.write(json.dumps(
                    {"ts": time.time(), "step": self.step, "name": name,
                     "value": float(value)}) + "\n")
                self._jsonl.flush()

    def close(self):
        """Flush and release the sink (idempotent)."""
        if self._writer is not None:
            try:
                self._writer.close()
            finally:
                self._writer = None
        if self._jsonl is not None:
            try:
                self._jsonl.close()
            finally:
                self._jsonl = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
