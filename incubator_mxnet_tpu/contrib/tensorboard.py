"""TensorBoard logging hook (ref python/mxnet/contrib/tensorboard.py).

Writes scalar summaries via tensorboardX/tensorboard if installed, else
falls back to a JSONL event log readable by any dashboard.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        os.makedirs(logging_dir, exist_ok=True)
        self._writer = None
        self._jsonl = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # torch is baked in
            self._writer = SummaryWriter(logging_dir)
        except Exception:
            self._jsonl = open(os.path.join(logging_dir, "metrics.jsonl"), "a")
        self.step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            if self._writer is not None:
                self._writer.add_scalar(name, value, self.step)
            else:
                self._jsonl.write(json.dumps(
                    {"ts": time.time(), "step": self.step, name: value}) + "\n")
                self._jsonl.flush()
