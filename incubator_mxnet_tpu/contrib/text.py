"""Text utilities: vocabulary + token embeddings
(ref python/mxnet/contrib/text/{utils,vocab,embedding}.py).

File-based only (this image has zero egress): pretrained-embedding classes
load from local files in the standard ``token v1 v2 ...`` text format; the
reference's downloadable GloVe/fastText catalogs are out of scope and
raise a clear error.
"""
from __future__ import annotations

import re
from collections import Counter

import numpy as onp

from .. import ndarray as nd

__all__ = ["count_tokens_from_str", "Vocabulary", "TokenEmbedding",
           "CustomEmbedding", "register", "create", "get_pretrained_file_names"]

_EMBED_REGISTRY = {}


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """ref text/utils.py count_tokens_from_str."""
    source_str = re.sub(r"\s+", " ",
                        source_str.replace(seq_delim, token_delim)).strip()
    if to_lower:
        source_str = source_str.lower()
    counter = counter_to_update if counter_to_update is not None else Counter()
    counter.update(t for t in source_str.split(token_delim) if t)
    return counter


class Vocabulary:
    """Token <-> index mapping (ref text/vocab.py Vocabulary).

    Index 0 is the unknown token; ``reserved_tokens`` follow it; the rest
    are counter keys sorted by frequency (ties broken alphabetically).
    """

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        assert min_freq > 0
        self._unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        assert len(set(reserved_tokens)) == len(reserved_tokens), \
            "reserved tokens must not repeat"
        assert unknown_token not in reserved_tokens
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._reserved_tokens = reserved_tokens
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq:
                    break
                if tok != unknown_token and tok not in reserved_tokens:
                    self._idx_to_token.append(tok)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """ref vocab.py to_indices — unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise ValueError("token index %d out of range [0, %d)"
                                 % (i, len(self)))
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out


class TokenEmbedding(Vocabulary):
    """Pretrained embedding over a vocabulary (ref text/embedding.py).

    Loads ``token v1 v2 ...`` lines from a local file; tokens absent from
    the file get ``init_unknown_vec`` (zeros by default).
    """

    def __init__(self, file_path=None, vocabulary=None, init_unknown_vec=None,
                 encoding="utf8", **kwargs):
        counter = Counter(
            {t: 1 for t in (vocabulary.idx_to_token[1:] if vocabulary
                            else [])})
        super().__init__(counter if vocabulary else None, **kwargs)
        self._vec_len = 0
        self._token_to_vec = {}
        if file_path:
            self._load_embedding(file_path, encoding)
        if vocabulary is None and self._token_to_vec:
            # vocabulary FROM the file: all its tokens, file order
            for t in self._token_to_vec:
                if t not in self._token_to_idx:
                    self._token_to_idx[t] = len(self._idx_to_token)
                    self._idx_to_token.append(t)
        unk = init_unknown_vec(self._vec_len) if init_unknown_vec \
            else onp.zeros(self._vec_len, "float32")
        mat = onp.stack([self._token_to_vec.get(t, unk)
                         for t in self._idx_to_token]) if self._vec_len else \
            onp.zeros((len(self), 0), "float32")
        self._idx_to_vec = nd.array(mat)

    def _load_embedding(self, path, encoding):
        with open(path, encoding=encoding) as f:
            for ln, line in enumerate(f):
                parts = line.rstrip().split(" ")
                if len(parts) < 2:
                    continue
                tok, vals = parts[0], parts[1:]
                if self._vec_len == 0:
                    self._vec_len = len(vals)
                elif len(vals) != self._vec_len:
                    raise ValueError(
                        "line %d of %s has %d values, expected %d"
                        % (ln + 1, path, len(vals), self._vec_len))
                self._token_to_vec[tok] = onp.asarray(vals, "float32")

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """ref embedding.py get_vecs_by_tokens."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            if t not in self._token_to_idx and lower_case_backup:
                t = t.lower()
            idxs.append(self._token_to_idx.get(t, 0))
        vecs = self._idx_to_vec[onp.asarray(idxs)] if not single else \
            self._idx_to_vec[idxs[0]]
        return vecs

    def update_token_vectors(self, tokens, new_vectors):
        toks = [tokens] if isinstance(tokens, str) else tokens
        arr = onp.array(self._idx_to_vec.asnumpy())  # writable copy
        vec = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else onp.asarray(new_vectors)
        vec = vec.reshape(len(toks), -1)
        for t, v in zip(toks, vec):
            if t not in self._token_to_idx:
                raise ValueError("token %r not in the embedding" % t)
            arr[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(arr)


class CustomEmbedding(TokenEmbedding):
    """ref embedding.py CustomEmbedding — user-supplied embedding file."""


def register(cls):
    """ref embedding.py register."""
    _EMBED_REGISTRY[cls.__name__.lower()] = cls
    return cls


register(CustomEmbedding)


def create(embedding_name, **kwargs):
    """ref embedding.py create — named pretrained catalogs (glove/fasttext)
    require downloads and are unavailable in this zero-egress build; use
    CustomEmbedding with a local file."""
    name = embedding_name.lower()
    if name not in _EMBED_REGISTRY:
        raise ValueError(
            "embedding %r unavailable (downloadable catalogs are out of "
            "scope; have: %s — use CustomEmbedding with a local file)"
            % (embedding_name, sorted(_EMBED_REGISTRY)))
    return _EMBED_REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """ref embedding.py get_pretrained_file_names — empty catalogs here."""
    return {} if embedding_name is None else []
