"""Model serving / deployment export (ref src/c_api/c_predict_api.cc,
cpp-package inference, amalgamation).

The reference's deployment surface is a C predict API over its own graph
format. The TPU-native equivalent is a SERIALIZED COMPILED PROGRAM: the
whole forward pass (params baked in or passed as inputs) lowered to
StableHLO and serialized with jax.export — the portable artifact the XLA
ecosystem serves. The .mxtpu file this module writes is loadable:

- from Python anywhere JAX runs: ``load(path).predict(x)`` (round-trip
  tested in tests/test_serving.py)
- from C/C++ without Python: the payload is a standard jax.export
  serialization whose StableHLO module (``export_mlir`` extracts it) is
  consumable by any PJRT plugin through the PJRT C API — the same contract
  TF-Serving/IFRT production loaders use. DEMONSTRATED by
  ``tools/pjrt_serve.c`` (plain C, vendored ``pjrt_c_api.h``, dlopen
  only), which compiles and executes the exported module on a real TPU
  through the axon PJRT plugin with no Python in the serving process
  (tests/test_serving.py::test_pjrt_c_serving, full tier). This replaces
  c_predict_api.cc's role; the operator registry needed by the
  reference's C loader does not exist here by design (programs are
  self-contained).

Format: 8-byte magic "MXTPU\\x00v1" + jax.export bytes.
"""
from __future__ import annotations

import hashlib
import time as _time

import jax
import jax.export  # jax>=0.4.30 does not re-export the submodule lazily

from .. import aot
from .. import config
from ..gluon import _functional
from ..ndarray import NDArray
from ..telemetry import devstats

__all__ = ["export_model", "load", "export_mlir", "export_pjrt_bundle",
           "ServedModel"]

_MAGIC = b"MXTPU\x00v1"


def export_model(net, example_inputs, path, train_mode=False):
    """Serialize net's forward (params baked as constants) to ``path``.

    net: an initialized Gluon block. example_inputs: NDArray(s) fixing the
    input signature. Returns the ServedModel for immediate use.
    """
    if isinstance(example_inputs, NDArray):
        example_inputs = [example_inputs]
    params, param_arrs, pure_fn, _aux = _functional.make_pure_fn(
        net, train_mode=train_mode)
    param_datas = [a._data for a in param_arrs]
    key = jax.random.PRNGKey(0)

    def fwd(*xs):
        outs, _ = pure_fn(param_datas, list(xs), key)
        return outs[0] if len(outs) == 1 else tuple(outs)

    exp = jax.export.export(jax.jit(fwd))(
        *[x._data for x in example_inputs])
    with open(path, "wb") as f:
        f.write(_MAGIC + exp.serialize())
    return ServedModel(exp)


def load(path):
    """Load a .mxtpu artifact → ServedModel (≙ MXPredCreate)."""
    with open(path, "rb") as f:
        buf = f.read()
    if not buf.startswith(_MAGIC):
        raise ValueError("%s is not an mxtpu serving artifact" % path)
    return ServedModel(jax.export.deserialize(buf[len(_MAGIC):]))


def export_mlir(path):
    """The artifact's StableHLO module text (feed to PJRT C API loaders)."""
    return load(path).mlir_module()


def export_pjrt_bundle(artifact_path, out_dir):
    """Materialize the Python-free serving bundle for tools/pjrt_serve.c:
    ``module.mlir`` (the artifact's StableHLO) + ``compile_options.pb``
    (a serialized single-replica CompileOptionsProto — the opaque options
    blob PJRT_Client_Compile requires). After this one-time export step, a
    plain-C loader runs the model against any PJRT plugin with no Python
    anywhere in the serving process (ref c_predict_api.cc deployment)."""
    import os

    from jax._src import compiler as _compiler

    os.makedirs(out_dir, exist_ok=True)
    mlir_path = os.path.join(out_dir, "module.mlir")
    with open(mlir_path, "w") as f:
        f.write(export_mlir(artifact_path))
    opts = _compiler.get_compile_options(num_replicas=1, num_partitions=1)
    opts_path = os.path.join(out_dir, "compile_options.pb")
    with open(opts_path, "wb") as f:
        f.write(opts.SerializeAsString())
    return mlir_path, opts_path


class ServedModel:
    """≙ the reference's PredictorHandle (c_predict_api.cc).

    Dispatch goes through the process-wide aot.CACHE: the exported program
    is AOT-compiled ONCE per input signature (``jit(exp.call).lower()
    .compile()``) instead of re-building an ``Exported.call`` wrapper —
    and re-tracing its calling convention — on every chunk. Two
    ServedModels loaded from the same artifact share executables (the
    cache id is a digest of the serialized module), so a hot-reload of an
    unchanged model never recompiles a bucket.
    """

    def __init__(self, exported, model_id=None):
        self._exp = exported
        if model_id is None:
            try:
                payload = exported.mlir_module_serialized
            except Exception:
                payload = exported.serialize()
            model_id = "x" + hashlib.sha256(payload).hexdigest()[:20]
        self._model_id = model_id

    def _replica_device(self, replica):
        """The device data-parallel replica ``replica`` executes on
        (round-robin over the local device list), or None for replica 0 —
        replica 0 keeps the classic uncommitted single-device path, so a
        replicas=1 deployment is byte-identical to the pre-replica one.
        More replicas than devices warns ONCE: the wrap double-subscribes
        chips and duplicates executables (distinct cache keys per replica
        index), which is oversubscription the operator should see."""
        if not replica:
            return None
        devices = jax.devices()
        if int(replica) >= len(devices) and not getattr(
                self, "_wrap_warned", False):
            self._wrap_warned = True
            import logging
            logging.getLogger(__name__).warning(
                "ServedModel %s: replica index %d wraps onto the %d local "
                "device(s) — more batcher replicas than chips "
                "double-subscribes devices and duplicates compiled "
                "executables; lower MXTPU_SERVE_REPLICAS",
                self._model_id, int(replica), len(devices))
        return devices[int(replica) % len(devices)]

    def _run(self, *datas, replica=0):
        """One compiled execution at the exact signature of ``datas``,
        through the shared executable cache. ``replica`` pins the
        executable (and the inputs) to that replica's device, so N
        batcher replicas drive N chips concurrently — each (signature,
        device) pair is its own cache entry, all prewarmed by the
        registry's (bucket x replica) warm loop."""
        dev = self._replica_device(replica)
        extra = () if dev is None else ("dev", dev.id)
        key = aot.cache_key(self._model_id, aot.input_signature(datas),
                            kind="serve", extra=extra)
        exp = self._exp

        def build():
            if dev is None:
                specs = [jax.ShapeDtypeStruct(d.shape, d.dtype)
                         for d in datas]
            else:
                from jax.sharding import SingleDeviceSharding
                sh = SingleDeviceSharding(dev)
                specs = [jax.ShapeDtypeStruct(d.shape, d.dtype, sharding=sh)
                         for d in datas]
            return (jax.jit(exp.call).lower(*specs).compile(),
                    None, None)       # the .mxtpu file IS the artifact

        if dev is not None:
            # the compiled program is committed to the replica's device;
            # inputs must arrive on it (host numpy from the batcher pays
            # the same one copy it paid to device 0 before)
            datas = [jax.device_put(d, dev) for d in datas]
        entry = aot.compile_cached(key, build)
        t0 = _time.perf_counter()
        out = entry.fn(*datas)
        # device-truth MFU needs a block-until-ready span. Under the
        # batcher (an ambient dispatch context, which also provides the
        # serving labels) the outputs are materialized host-side
        # immediately after, so the sync moves cost rather than adding
        # any — always observe there. A DIRECT predict() caller keeps
        # async dispatch unless MXTPU_DEVSTATS_EVAL_SYNC opts in (the
        # same overlap contract as jit.EvalStep).
        if entry.stats is not None and (
                devstats.in_dispatch_context()
                or config.get_env("MXTPU_DEVSTATS_EVAL_SYNC")):
            try:
                jax.block_until_ready(out)
            except Exception:
                pass
            devstats.observe_dispatch("serve", entry.stats,
                                      _time.perf_counter() - t0,
                                      model=self._model_id,
                                      replica=int(replica))
        return out

    @property
    def input_shapes(self):
        return [tuple(a.shape) for a in self._exp.in_avals]

    @property
    def output_shapes(self):
        return [tuple(a.shape) for a in self._exp.out_avals]

    def mlir_module(self):
        """StableHLO module text of the compiled program."""
        return self._exp.mlir_module()

    def predict(self, *inputs):
        """≙ MXPredSetInput + MXPredForward + MXPredGetOutput."""
        import numpy as onp
        # array-likes (jax device arrays included) pass through untouched
        # — asarray would force a device->host copy; only list/scalar
        # payloads need materializing (the cache key wants .shape/.dtype)
        datas = [x._data if isinstance(x, NDArray)
                 else x if hasattr(x, "shape") and hasattr(x, "dtype")
                 else onp.asarray(x)
                 for x in inputs]
        out = self._run(*datas)
        if isinstance(out, (list, tuple)):
            return tuple(NDArray(o) for o in out)
        return NDArray(out)

    @property
    def batch_size(self):
        """The exported batch-axis extent (dim 0 of the first input)."""
        shp = self.input_shapes[0]
        if not shp:
            raise ValueError("exported model has a rank-0 input — no "
                             "batch axis to serve over")
        return int(shp[0])

    def predict_batch(self, *stacked_inputs, replica=0):
        """Serving-batcher entry point: run ``n`` stacked items (dim 0)
        through the FIXED exported batch shape by re-chunking.

        The artifact compiled exactly one batch size ``B``; a dynamic
        batcher produces buckets of any size. Inputs are split into
        ceil(n/B) chunks, the last chunk padded to ``B`` by repeating its
        final row (shape/dtype-exact, values in-distribution), and outputs
        are concatenated with the padding rows dropped — so callers see a
        true dim-0 batch axis whatever ``B`` was. Returns a tuple of
        numpy arrays (host-side: results go straight onto the wire).

        ``replica`` (declared, so the batcher and registry forward it)
        pins this dispatch to the replica's device — N data-parallel
        batcher workers drive N chips concurrently (docs/SERVING.md).
        """
        import numpy as onp

        B = self.batch_size
        ins = [onp.asarray(x._data if isinstance(x, NDArray) else x)
               for x in stacked_inputs]
        avals = self._exp.in_avals
        ins = [x.astype(a.dtype, copy=False) for x, a in zip(ins, avals)]
        n = ins[0].shape[0]
        out_chunks = []
        for lo in range(0, n, B):
            chunk = [x[lo:lo + B] for x in ins]
            pad = B - chunk[0].shape[0]
            if pad:
                chunk = [onp.concatenate([c, onp.repeat(c[-1:], pad, axis=0)])
                         for c in chunk]
            out = self._run(*chunk, replica=replica)
            outs = out if isinstance(out, (list, tuple)) else (out,)
            out_chunks.append([onp.asarray(o)[:B - pad] for o in outs])
        return tuple(onp.concatenate([ch[i] for ch in out_chunks])
                     for i in range(len(out_chunks[0])))
