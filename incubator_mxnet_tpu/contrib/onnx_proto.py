"""Self-contained ONNX protobuf codec (no `onnx` package in this image).

Implements the wire format (varint / length-delimited fields) for the subset
of onnx.proto3 messages the exporter/importer uses. Field numbers follow the
stable ONNX IR schema (onnx/onnx.proto, IR version 8 era):

  ModelProto:   ir_version=1, producer_name=2, producer_version=3, graph=7,
                opset_import=8
  OperatorSetIdProto: domain=1, version=2
  GraphProto:   node=1, name=2, initializer=5, input=11, output=12,
                value_info=13
  NodeProto:    input=1, output=2, name=3, op_type=4, attribute=5, domain=7
  AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8, strings=9,
                type=20  (FLOAT=1 INT=2 STRING=3 TENSOR=4 FLOATS=6 INTS=7
                STRINGS=8)
  TensorProto:  dims=1, data_type=2, name=8, raw_data=9
                (FLOAT=1 UINT8=2 INT8=3 INT32=6 INT64=7 BOOL=9 FLOAT16=10
                 DOUBLE=11 BFLOAT16=16)
  ValueInfoProto: name=1, type=2
  TypeProto:    tensor_type=1;  TypeProto.Tensor: elem_type=1, shape=2
  TensorShapeProto: dim=1;  Dimension: dim_value=1, dim_param=2
"""
from __future__ import annotations

import struct

import numpy as onp

# ---------------------------------------------------------------- wire
def _varint(n):
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def f_varint(field, value):
    return _tag(field, 0) + _varint(int(value))


def f_bytes(field, data):
    if isinstance(data, str):
        data = data.encode()
    return _tag(field, 2) + _varint(len(data)) + data


def f_float(field, value):
    return _tag(field, 5) + struct.pack("<f", float(value))


def parse(buf):
    """Generic decode: {field: [values]}; length-delimited values stay bytes."""
    out = {}
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError("unsupported wire type %d" % wire)
        out.setdefault(field, []).append(v)
    return out


def _signed(v):
    """Protobuf int64: negative values ride as 10-byte unsigned varints."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _read_varint(buf, i):
    shift, val = 0, 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


# ---------------------------------------------------------------- dtypes
DT_FLOAT, DT_UINT8, DT_INT8, DT_INT32, DT_INT64 = 1, 2, 3, 6, 7
DT_BOOL, DT_FLOAT16, DT_DOUBLE, DT_BFLOAT16 = 9, 10, 11, 16

_NP2ONNX = {"float32": DT_FLOAT, "uint8": DT_UINT8, "int8": DT_INT8,
            "int32": DT_INT32, "int64": DT_INT64, "bool": DT_BOOL,
            "float16": DT_FLOAT16, "float64": DT_DOUBLE,
            "bfloat16": DT_BFLOAT16}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


# ---------------------------------------------------------------- messages
def tensor(name, arr):
    arr = onp.ascontiguousarray(arr)
    dt = _NP2ONNX[str(arr.dtype)]
    body = b"".join(f_varint(1, d) for d in arr.shape)
    body += f_varint(2, dt)
    body += f_bytes(8, name)
    body += f_bytes(9, arr.tobytes())  # raw_data covers bf16 too (2B/elem)
    return body


def attr_int(name, v):
    return f_bytes(1, name) + f_varint(3, v) + f_varint(20, 2)


def attr_float(name, v):
    return f_bytes(1, name) + f_float(2, v) + f_varint(20, 1)


def attr_string(name, v):
    return f_bytes(1, name) + f_bytes(4, v) + f_varint(20, 3)


def attr_ints(name, vs):
    return (f_bytes(1, name) + b"".join(f_varint(8, v) for v in vs)
            + f_varint(20, 7))


def attr_tensor(name, arr):
    return f_bytes(1, name) + f_bytes(5, tensor("", arr)) + f_varint(20, 4)


def attr_strings(name, vs):
    return (f_bytes(1, name) + b"".join(f_bytes(9, v) for v in vs)
            + f_varint(20, 8))


def node(op_type, inputs, outputs, name="", attrs=()):
    body = b"".join(f_bytes(1, i) for i in inputs)
    body += b"".join(f_bytes(2, o) for o in outputs)
    if name:
        body += f_bytes(3, name)
    body += f_bytes(4, op_type)
    body += b"".join(f_bytes(5, a) for a in attrs)
    return body


def value_info(name, shape, dtype="float32"):
    dims = b"".join(f_bytes(1, f_varint(1, d)) for d in shape)
    shp = dims
    tensor_type = f_varint(1, _NP2ONNX[str(dtype)]) + f_bytes(2, shp)
    type_proto = f_bytes(1, tensor_type)
    return f_bytes(1, name) + f_bytes(2, type_proto)


def graph(name, nodes, inputs, outputs, initializers):
    body = b"".join(f_bytes(1, n) for n in nodes)
    body += f_bytes(2, name)
    body += b"".join(f_bytes(5, t) for t in initializers)
    body += b"".join(f_bytes(11, i) for i in inputs)
    body += b"".join(f_bytes(12, o) for o in outputs)
    return body


def model(graph_bytes, opset=13, producer="incubator_mxnet_tpu"):
    opset_b = f_bytes(1, "") + f_varint(2, opset)
    return (f_varint(1, 8)              # ir_version 8
            + f_bytes(2, producer)
            + f_bytes(7, graph_bytes)
            + f_bytes(8, opset_b))


# ---------------------------------------------------------------- readers
def read_model(buf):
    m = parse(buf)
    g = parse(m[7][0])
    return {
        "ir_version": m.get(1, [0])[0],
        "producer": m.get(2, [b""])[0].decode(),
        "graph": g,
    }


def read_nodes(g):
    out = []
    for nb in g.get(1, []):
        n = parse(nb)
        attrs = {}
        for ab in n.get(5, []):
            a = parse(ab)
            aname = a[1][0].decode()
            atype = a.get(20, [0])[0]
            if atype == 2:
                attrs[aname] = _signed(a[3][0])
            elif atype == 1:
                attrs[aname] = a[2][0]
            elif atype == 3:
                attrs[aname] = a[4][0].decode()
            elif atype == 7:
                attrs[aname] = [_signed(int(v)) for v in a.get(8, [])]
            elif atype == 8:
                attrs[aname] = [v.decode() for v in a.get(9, [])]
            elif atype == 4:
                attrs[aname] = read_tensor(parse(a[5][0]))
        out.append({
            "op_type": n[4][0].decode(),
            "inputs": [x.decode() for x in n.get(1, [])],
            "outputs": [x.decode() for x in n.get(2, [])],
            "name": n.get(3, [b""])[0].decode(),
            "attrs": attrs,
        })
    return out


def read_tensor(t):
    dims = tuple(int(d) for d in t.get(1, []))
    dt = t.get(2, [DT_FLOAT])[0]
    name = t.get(8, [b""])[0].decode()
    raw = t.get(9, [b""])[0]
    if _ONNX2NP[dt] == "bfloat16":
        import ml_dtypes
        arr = onp.frombuffer(raw, ml_dtypes.bfloat16).reshape(dims)
    else:
        arr = onp.frombuffer(raw, _ONNX2NP[dt]).reshape(dims)
    return name, arr


def read_initializers(g):
    return dict(read_tensor(parse(tb)) for tb in g.get(5, []))


def read_value_infos(g, field):
    out = []
    for vb in g.get(field, []):
        v = parse(vb)
        name = v[1][0].decode()
        shape, dtype = (), "float32"
        if 2 in v:
            tp = parse(v[2][0])
            if 1 in tp:
                tt = parse(tp[1][0])
                dtype = _ONNX2NP.get(tt.get(1, [DT_FLOAT])[0], "float32")
                if 2 in tt:
                    dims = []
                    for db in parse(tt[2][0]).get(1, []):
                        d = parse(db)
                        dims.append(int(d.get(1, [0])[0]))
                    shape = tuple(dims)
        out.append((name, shape, dtype))
    return out
