"""SVRG optimization (ref python/mxnet/contrib/svrg_optimization/
svrg_module.py SVRGModule + svrg_optimizer.py).

Stochastic Variance-Reduced Gradient: every ``update_freq`` epochs the
module snapshots the parameters (w~) and computes the FULL gradient mu over
the epoch's data; each minibatch step then uses
``g_i(w) - g_i(w~) + mu`` — an unbiased, variance-reduced gradient.

TPU note: both the live and the snapshot forward/backward are ordinary
compiled steps; the correction is pure elementwise arithmetic XLA fuses
into the update.
"""
from __future__ import annotations

import logging

from ..module.module import Module
from .. import ndarray as nd

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Drop-in Module with SVRG updates (ref svrg_module.py:35)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging, context=None,
                 update_freq=2, **kwargs):
        super().__init__(symbol, data_names=data_names,
                         label_names=label_names, logger=logger,
                         context=context, **kwargs)
        assert update_freq >= 1
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context, **kwargs)
        self._mu = None  # full-gradient snapshot {name: NDArray}

    # -- lifecycle mirrors the main module onto the snapshot module -----
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        self._mod_aux.bind(data_shapes, label_shapes, for_training,
                           inputs_need_grad, force_rebind, None, grad_req)

    def init_params(self, *args, **kwargs):
        super().init_params(*args, **kwargs)
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg, aux)

    def update_full_grads(self, train_data):
        """Snapshot params into the aux module and accumulate mu over the
        whole iterator (ref svrg_module.py update_full_grads)."""
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg, aux)
        train_data.reset()
        params = set(self._mod_aux.param_names)  # NEVER input/data grads
        sums, nbatch = {}, 0
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            for name, g in self._mod_aux._exec.grad_dict.items():
                if g is None or name not in params:
                    continue
                sums[name] = g.copy() if name not in sums else sums[name] + g
            nbatch += 1
        self._mu = {k: v / nbatch for k, v in sums.items()}

    def forward_backward(self, data_batch):
        """Main fwd/bwd + snapshot fwd/bwd; grads become g - g~ + mu."""
        super().forward_backward(data_batch)
        if self._mu is None:
            return  # before the first full-grad pass: plain SGD step
        self._mod_aux.forward(data_batch, is_train=True)
        self._mod_aux.backward()
        params = set(self.param_names)
        for name, g in self._exec.grad_dict.items():
            if g is None or name not in self._mu or name not in params:
                continue
            g_tilde = self._mod_aux._exec.grad_dict.get(name)
            if g_tilde is not None:
                g._data = (g - g_tilde + self._mu[name])._data

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, **kwargs):
        """ref svrg_module.py fit — the classic loop with a full-grad pass
        every ``update_freq`` epochs."""
        from .. import metric as metric_mod
        from .. import initializer as init_mod
        assert num_epoch is not None
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        self.init_params(initializer=initializer or init_mod.Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            if epoch % self.update_freq == 0:
                self.update_full_grads(train_data)
            eval_metric.reset()
            train_data.reset()
            for data_batch in train_data:
                self.forward_backward(data_batch)
                self.update()
                self.update_metric(eval_metric, data_batch.label)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if eval_data is not None:
                res = self.score(eval_data, eval_metric)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
