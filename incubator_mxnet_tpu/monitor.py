"""Monitor — tap intermediate outputs during training
(ref python/mxnet/monitor.py, SetMonitorCallback graph_executor.cc:187).

TPU-native: installs forward hooks on Blocks (imperative) or wraps Executor
outputs (symbolic); stat_func runs on host after a device sync.
"""
from __future__ import annotations

import logging
import re

from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return nd.norm(x) / (x.size ** 0.5)
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort

    def install(self, block):
        """Hook a gluon Block tree (the SetMonitorCallback analog)."""
        def hook(blk, inputs, output):
            if self.activated and self.re_pattern.match(blk.name):
                outs = output if isinstance(output, (list, tuple)) else [output]
                for i, o in enumerate(outs):
                    if isinstance(o, NDArray):
                        # copy at enqueue: the live output may sit in a
                        # donated buffer the next compiled step overwrites
                        # in place — stats computed at toc() would then
                        # read the NEXT step's bytes. jax arrays are
                        # immutable, but o._data is REBOUND by in-place
                        # ops; NDArray.copy() pins this step's value.
                        self.queue.append((self.step,
                                           "%s_output%d" % (blk.name, i),
                                           o.copy()))
        def walk(b):
            b.register_forward_hook(hook)
            for c in b._children.values():
                walk(c)
        walk(block)

    def install_exec(self, executor):
        self.exes.append(executor)

    def tic(self):
        """ref monitor.py tic — begin collecting for this batch."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """ref monitor.py toc — collect stats, return list of (step,name,stat)."""
        if not self.activated:
            return []
        for exe in self.exes:
            for i, o in enumerate(getattr(exe, "outputs", [])):
                self.queue.append((self.step, "output%d" % i, o))
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda x: x[1])
        for n, k, v_arr in queue:
            assert isinstance(v_arr, NDArray)
            v = self.stat_func(v_arr)
            res.append((n, k, v))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k,
                         str(v.asnumpy() if isinstance(v, NDArray) else v))
