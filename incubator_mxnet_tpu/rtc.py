"""Runtime kernel compilation (ref python/mxnet/rtc.py CudaModule/NVRTC,
src/common/rtc.cc).

TPU-native: user runtime kernels are Pallas kernels, not CUDA source. A
PallasModule compiles a user-supplied Pallas kernel function at runtime with
the same module/get_kernel/launch UX the reference offered for NVRTC.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ndarray import NDArray, _apply

__all__ = ["PallasModule", "CudaModule"]


class PallasModule:
    """Runtime-compiled device kernels from a Pallas function."""

    def __init__(self, kernel_fn, out_shape_fn=None):
        """kernel_fn(*refs) in pallas style; out_shape_fn(*arrs)->ShapeDtypeStruct."""
        self._kernel_fn = kernel_fn
        self._out_shape_fn = out_shape_fn

    def get_kernel(self, name=None, signature=None):
        return PallasKernel(self._kernel_fn, self._out_shape_fn)


class PallasKernel:
    def __init__(self, kernel_fn, out_shape_fn):
        self._kernel_fn = kernel_fn
        self._out_shape_fn = out_shape_fn

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        from jax.experimental import pallas as pl

        arrs = [a._data if isinstance(a, NDArray) else jnp.asarray(a) for a in args]
        out_shape = (self._out_shape_fn(*arrs) if self._out_shape_fn
                     else jax.ShapeDtypeStruct(arrs[0].shape, arrs[0].dtype))
        fn = pl.pallas_call(self._kernel_fn, out_shape=out_shape,
                            grid=grid_dims if grid_dims else None)
        return NDArray(fn(*arrs))


class CudaModule:
    """Compatibility shim: CUDA source modules cannot run on TPU."""

    def __init__(self, source, options=(), exports=()):
        raise RuntimeError(
            "CudaModule (NVRTC) is CUDA-only; on TPU use rtc.PallasModule with "
            "a Pallas kernel function instead.")
