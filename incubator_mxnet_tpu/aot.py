"""Process-wide AOT compiled-executable cache (ROADMAP item 3: p99 must
not see a compile).

Before this module, three independent lazy caches each paid their own
trace+compile+first-run window inside the first hot-path call: TrainStep
``self._cache``, EvalStep ``self._cache``, and the per-ServedModel
``Exported.call`` path (which re-built its call wrapper every chunk).
Under bucketed serving that is one full compile *per bucket, per model
version, per component, per process* — and a registry hot-reload put that
window straight into user-visible p99.

This module replaces them with ONE shared cache:

- **Key**: ``(model_id, kind, input signature, mesh, extra)`` — the
  shape-bucket × dtype × mesh identity of a compiled program
  (``cache_key()``). ``model_id`` is a stable digest (``model_id_for()``)
  so two components serving the same architecture share executables
  instead of recompiling per component.
- **Compilation**: JAX's explicit AOT pipeline —
  ``jit(fn).lower(*args).compile()`` — instead of first-call lazy
  compilation, so the compile lands where the caller schedules it
  (a prewarm thread, a build span), never inside a later dispatch.
- **Artifacts** (``MXTPU_AOT_CACHE_DIR``): exportable programs (the
  eval/serve forward paths) are serialized via ``jax.export`` (StableHLO)
  per cache key — including MESH-SHARDED serving programs, whose
  partitioned module jax.export records with its GSPMD shardings (the
  key's mesh signature is in the file digest, so topology mismatches
  miss instead of misload). A fresh process pointed at a populated cache
  dir LOADS the program instead of re-tracing the Python model — the
  first request pays zero trace time and records an artifact hit, and
  with registry prewarm the XLA compile of the loaded module also lands
  pre-traffic. Train-kind entries (donated-buffer programs,
  instance-bound state) stay in-memory only.
- **Eviction**: LRU by last-dispatch time, bounded by
  ``MXTPU_AOT_CACHE_SIZE``, with every eviction counted on
  ``mxtpu_aot_evictions_total`` so silent thrash is visible (dict-order
  eviction could silently drop the hottest bucket).

- **Device truth** (telemetry/devstats.py): every executable entering the
  cache — fresh build OR artifact load — has its XLA ``cost_analysis()``
  + ``memory_analysis()`` harvested ONCE into ``entry.stats``
  (``{flops, bytes_accessed, peak_bytes, output_bytes}``), persisted in
  the artifact header (format v2) so a zero-compile load in a fresh
  process still knows its program's FLOPs, and published on
  ``mxtpu_aot_program_flops`` / ``mxtpu_aot_program_peak_bytes``
  ``{model,kind,bucket}``. The hot paths divide these FLOPs by measured
  dispatch spans for MFU attribution — analysis happens here, at
  build/load time, never per dispatch (mxtpulint R001 models the
  per-dispatch form as a defect).

Observability: ``mxtpu_aot_{hits,misses,evictions,artifact_hits,
artifact_writes}_total`` counters, the ``mxtpu_aot_entries`` gauge, and
``aot:load`` spans around artifact deserialization (prewarm emits
``aot:warm`` spans from serving/registry.py). See docs/AOT.md.
"""
from __future__ import annotations

import hashlib
import json as _json
import logging
import os
import struct
import threading
import time as _time
from collections import namedtuple

from . import config
from . import telemetry
from .telemetry import devstats, faultlab, spans

__all__ = ["CacheKey", "cache_key", "AOTCache", "CACHE", "compile_cached",
           "model_id_for", "input_signature", "mesh_sig", "artifact_path",
           "ARTIFACT_MAGIC", "FORMAT_VERSION", "collect_inserts",
           "ProgramFactsRef", "program_digest", "facts_for_key"]

_LOG = logging.getLogger(__name__)

#: bump when the artifact payload layout changes — old files are ignored,
#: never misparsed (the version participates in the file digest AND the
#: magic, so a stale same-named file is rejected at the magic check).
#: v2: a length-prefixed JSON header (program stats from cost/memory
#: analysis) sits between the magic and the jax.export payload, so a
#: zero-compile artifact load still carries device truth.
FORMAT_VERSION = 2
ARTIFACT_MAGIC = b"MXTPUAOT\x002"

_HITS = telemetry.counter(
    "mxtpu_aot_hits_total",
    "Shared executable-cache hits (dispatch found a compiled program).",
    ("kind",))
_MISSES = telemetry.counter(
    "mxtpu_aot_misses_total",
    "Shared executable-cache misses (artifact load or fresh build).",
    ("kind",))
_EVICTIONS = telemetry.counter(
    "mxtpu_aot_evictions_total",
    "LRU evictions from the shared executable cache past "
    "MXTPU_AOT_CACHE_SIZE — a climbing rate under steady traffic means "
    "the bound is too small for the live bucket set (cache thrash).",
    ("kind",))
_ARTIFACT_HITS = telemetry.counter(
    "mxtpu_aot_artifact_hits_total",
    "Cache misses satisfied by a persisted jax.export artifact "
    "(MXTPU_AOT_CACHE_DIR) instead of re-tracing the model.", ("kind",))
_ARTIFACT_WRITES = telemetry.counter(
    "mxtpu_aot_artifact_writes_total",
    "Serialized executables written to MXTPU_AOT_CACHE_DIR.", ("kind",))
_ENTRIES = telemetry.gauge(
    "mxtpu_aot_entries",
    "Live entries in the process-wide AOT executable cache.")
_PROG_FLOPS = telemetry.gauge(
    "mxtpu_aot_program_flops",
    "XLA cost_analysis FLOPs of one execution of a cached program, "
    "harvested at build/load time (artifact loads carry it in the v2 "
    "header). The numerator of every mxtpu_device_mfu observation — "
    "nonzero after a zero-compile artifact-only load is the device-truth "
    "survival contract (docs/AOT.md).", ("model", "kind", "bucket"))
_PROG_PEAK_BYTES = telemetry.gauge(
    "mxtpu_aot_program_peak_bytes",
    "memory_analysis peak live bytes of one execution of a cached "
    "program (arguments + outputs + XLA temp buffers, donated/aliased "
    "bytes deducted) — compare against mxtpu_device_memory_bytes "
    "bytes_limit before sizing batch buckets.", ("model", "kind",
                                                 "bucket"))

#: (model_id, kind, input_sig, mesh, extra) — the full identity of one
#: compiled program. kind is 'train' | 'eval' | 'serve'; input_sig is a
#: tuple of (shape tuple, dtype string) per input; mesh is mesh_sig();
#: extra carries caller-specific statics (e.g. TrainStep's n_net_inputs).
CacheKey = namedtuple("CacheKey", ("model_id", "kind", "input_sig", "mesh",
                                   "extra"))


def input_signature(arrs):
    """(shape, dtype) tuple per input — accepts NDArrays, jax or numpy
    arrays (anything with .shape/.dtype)."""
    return tuple((tuple(a.shape), str(a.dtype)) for a in arrs)


def mesh_sig(mesh):
    """Hashable identity of a mesh (None for single-device): axis sizes +
    device count, enough to distinguish programs compiled for different
    layouts."""
    if mesh is None:
        return None
    return (tuple(sorted(mesh.shape.items())), len(mesh.devices.flat))


def cache_key(model_id, input_sig, kind="eval", mesh=None, extra=()):
    """Build the canonical CacheKey. ``input_sig`` comes from
    ``input_signature()`` (already normalized) or any iterable of
    (shape, dtype) pairs."""
    sig = tuple((tuple(s), str(d)) for s, d in input_sig)
    return CacheKey(str(model_id), str(kind), sig,
                    mesh if (mesh is None or isinstance(mesh, tuple))
                    else mesh_sig(mesh), tuple(extra))


def _iter_blocks(net, path="net", seen=None):
    """Depth-first (path, block) walk over a Gluon block tree."""
    if seen is None:
        seen = set()
    if id(net) in seen:
        return
    seen.add(id(net))
    yield path, net
    children = getattr(net, "_children", None)
    if isinstance(children, dict):
        for name, child in sorted(children.items()):
            yield from _iter_blocks(child, "%s.%s" % (path, name), seen)


def _is_array(val):
    return hasattr(val, "shape") and hasattr(val, "dtype") \
        and hasattr(val, "__array__")


def _baked_state_tokens(net):
    """Digest tokens for TRACE-TIME-BAKED block state: instance attributes
    that are Python scalars or raw arrays (NOT registered Parameters —
    those stay runtime inputs). A quantized wrapper's int8 weights and
    calibration ranges live here; two differently-calibrated instances of
    one architecture must NOT share a compiled program, and a reloaded
    identical one must."""
    import numpy as onp
    scalars = (bool, int, float, str, bytes, type(None))
    skip = ("_children", "_reg_params", "_forward_hooks", "_cached_fn",
            "_forward_pre_hooks", "_prefix", "_name", "_scope")
    for path, block in _iter_blocks(net):
        try:
            items = sorted(vars(block).items())
        except TypeError:
            continue
        for name, val in items:
            if name in skip or type(val).__name__ == "Parameter" \
                    or hasattr(val, "_children"):
                continue
            if isinstance(val, dict):
                # sort by repr: mixed-type keys (int vs str) make the
                # natural sort raise mid-generator, which would silently
                # truncate the digest and merge differently-baked models
                items = tuple(sorted(
                    ((k, v) for k, v in val.items()
                     if isinstance(v, scalars)),
                    key=repr))
                yield "%s.%s=%r" % (path, name, items)
                continue
            if isinstance(val, (tuple, list)) \
                    and all(isinstance(v, scalars) for v in val):
                yield "%s.%s=%r" % (path, name, tuple(val))
            elif isinstance(val, scalars):
                yield "%s.%s=%r" % (path, name, val)
            elif _is_array(val) or hasattr(val, "_data"):
                try:
                    arr = onp.asarray(getattr(val, "_data", val))
                    yield "%s.%s@%s" % (path, name, hashlib.sha256(
                        arr.tobytes()).hexdigest()[:16])
                except Exception:
                    yield "%s.%s@<unhashable>" % (path, name)


def model_id_for(net, extra=()):
    """Stable content digest of a Gluon block: class, repr (layer
    hyperparameters), the parameter (name, shape, dtype) list, and a hash
    of any trace-time-baked instance state (raw arrays / scalars that are
    not Parameters), plus caller ``extra`` tokens. Components
    (EvalStep/BlockServable) built on an identical model produce the same
    id and SHARE compiled executables — and a fresh process reconstructing
    the same model resolves the same persisted artifact. Registered
    Parameters stay runtime inputs, so sharing is weight-safe.

    The digest cannot see forward() semantics hidden from repr, the
    parameter structure, and the baked-state walk (e.g. state tucked in
    nested custom containers) — pass an explicit ``model_id`` to the
    caller (EvalStep/TrainStep/export) when such models must not share
    (docs/AOT.md invalidation rules).
    """
    import jax
    parts = [jax.__version__, type(net).__qualname__]
    try:
        parts.append(repr(net))
    except Exception:
        parts.append("<repr-failed>")
    try:
        # POSITIONAL (index, shape, dtype) — never the parameter names:
        # gluon auto-naming makes every instance's prefix unique
        # (dense0_ vs dense1_), and two instances of one architecture
        # must produce the same id; collect_params() walk order is
        # structure-deterministic, which is what make_pure_fn's input
        # ordering relies on too
        for i, p in enumerate(net.collect_params().values()):
            shape = getattr(p, "shape", None)
            dtype = getattr(p, "dtype", None)
            parts.append("p%d:%s:%s" % (i, shape, dtype))
    except Exception:
        parts.append("<params-unavailable>")
    try:
        parts.extend(_baked_state_tokens(net))
    except Exception:
        parts.append("<baked-state-unavailable>")
    parts.extend(str(e) for e in extra)
    return "g" + hashlib.sha256("\x00".join(parts).encode()).hexdigest()[:20]


class _Entry:
    """One compiled program + its caller extras and LRU bookkeeping.
    ``stats`` is the program's device truth (devstats.program_stats dict:
    flops / bytes_accessed / peak_bytes / output_bytes) or None when the
    program is not analyzable (a lazily-jitted or wrapped callable)."""

    __slots__ = ("key", "fn", "extras", "last_used", "source", "created",
                 "stats")

    def __init__(self, key, fn, extras, source, stats=None):
        self.key = key
        self.fn = fn
        self.extras = extras
        self.source = source            # 'build' | 'artifact'
        self.stats = stats
        self.created = _time.monotonic()
        self.last_used = self.created


_collector = threading.local()


class collect_inserts:
    """Record every cache entry THIS THREAD inserts while the context is
    active. The serving registry wraps each prewarm bucket's warm
    dispatches in one so the hlolint load gate can lint exactly the
    programs the warm just produced (build or artifact load) before it
    repoints traffic at them — no cache-wide diffing, no cross-thread
    attribution guesswork (warm dispatches run on the one warm thread).
    Nests: the inner context collects; the outer resumes afterwards."""

    def __enter__(self):
        self._prev = getattr(_collector, "sink", None)
        self.entries = []
        _collector.sink = self.entries
        return self.entries

    def __exit__(self, *exc):
        _collector.sink = self._prev
        return False


class AOTCache:
    """Thread-safe LRU map CacheKey -> _Entry (the process-wide instance
    is ``aot.CACHE``). Lookups touch last_used; inserts evict
    least-recently-DISPATCHED entries past MXTPU_AOT_CACHE_SIZE and count
    each eviction."""

    def __init__(self):
        self._lock = threading.RLock()
        self._entries = {}
        self._building = {}   # key -> Event (single-flight build guard)

    # ------------------------------------------------------------------
    def lookup(self, key):
        """Hit -> entry (last_used touched, hit counted); miss -> None."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.last_used = _time.monotonic()
        if entry is not None:
            _HITS.inc(kind=key.kind)
        return entry

    def peek(self, key):
        """lookup() without touching LRU order or counters (tests,
        inspection)."""
        with self._lock:
            return self._entries.get(key)

    def insert(self, key, fn, extras=None, source="build", stats=None):
        if stats is None:
            # device truth is harvested HERE, once per cache entry — the
            # one place every executable (train/eval/serve, build or
            # artifact) passes through on its way to a dispatch
            stats = devstats.program_stats(fn)
        entry = _Entry(key, fn, extras, source, stats)
        with self._lock:
            self._entries[key] = entry
            self._evict_locked()
            _ENTRIES.set(len(self._entries))
            # publish INSIDE the lock: outside it, a concurrent
            # clear()/discard() could unpublish first and this late
            # publish would resurrect a series with no backing entry
            # (lock order cache->gauge matches _unpublish_locked)
            if stats:
                _publish_program_stats(key, stats)
        sink = getattr(_collector, "sink", None)
        if sink is not None:
            sink.append(entry)
        return entry

    def _evict_locked(self):
        bound = max(1, config.get_env("MXTPU_AOT_CACHE_SIZE"))
        while len(self._entries) > bound:
            victim = min(self._entries.values(),
                         key=lambda e: e.last_used)
            self._entries.pop(victim.key)
            _EVICTIONS.inc(kind=victim.key.kind)
            self._unpublish_locked(victim.key)

    def _unpublish_locked(self, key):
        """Drop the departed entry's program-stats gauge series — a dead
        program must not export frozen FLOPs forever (same discipline as
        serving's detach_telemetry). Several entries can share one
        (model, kind, bucket) label set (per-replica device pins, dtype
        variants): when a live entry still maps onto it, the gauges are
        RE-published from that survivor's stats (the departed entry may
        have published last, and the label must describe a program that
        is actually in the cache). Caller holds self._lock."""
        label = (key.model_id, key.kind, _bucket_of(key))
        for other_key, other in self._entries.items():
            if (other_key.model_id, other_key.kind,
                    _bucket_of(other_key)) == label and other.stats:
                _publish_program_stats(other_key, other.stats)
                return
        try:
            _PROG_FLOPS.remove(model=label[0], kind=label[1],
                               bucket=label[2])
            _PROG_PEAK_BYTES.remove(model=label[0], kind=label[1],
                                    bucket=label[2])
        except Exception:
            _LOG.debug("program stats gauge removal dropped",
                       exc_info=True)

    def discard(self, key):
        with self._lock:
            gone = self._entries.pop(key, None) is not None
            if gone:
                self._unpublish_locked(key)
            _ENTRIES.set(len(self._entries))
        return gone

    def clear(self):
        with self._lock:
            keys = list(self._entries)
            self._entries.clear()
            for key in keys:
                self._unpublish_locked(key)
            _ENTRIES.set(0)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def keys(self):
        with self._lock:
            return list(self._entries)

    def snapshot(self):
        """JSON-able view (GET /debug/aot): one record per entry, most
        recently dispatched first."""
        now = _time.monotonic()
        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: -e.last_used)
            return [{"model_id": e.key.model_id, "kind": e.key.kind,
                     "input_sig": [[list(s), d] for s, d in e.key.input_sig],
                     "mesh": e.key.mesh if e.key.mesh is None
                     else list(e.key.mesh),
                     "source": e.source,
                     "stats": dict(e.stats) if e.stats else None,
                     "age_s": round(now - e.created, 3),
                     "idle_s": round(now - e.last_used, 3)}
                    for e in entries]

    # ------------------------------------------------------------------
    def get_or_build(self, key, build, exportable=False, arg_specs=None):
        """Single-flight miss path: at most one thread builds a given key;
        the rest wait on its completion event and then hit. ``build()``
        returns ``(fn, extras, exported_or_None)``; the exported program
        (when present and ``exportable``) is persisted to
        MXTPU_AOT_CACHE_DIR. A persisted artifact, when present, is
        loaded INSTEAD of calling build() — no Python tracing."""
        while True:
            entry = self.lookup(key)
            if entry is not None:
                return entry
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    entry.last_used = _time.monotonic()
                    _HITS.inc(kind=key.kind)
                    return entry
                event = self._building.get(key)
                if event is None:
                    event = self._building[key] = threading.Event()
                    builder = True
                else:
                    builder = False
            if not builder:
                # another thread owns the build — wait, then re-lookup
                # (bounded so a crashed builder cannot strand waiters)
                event.wait(timeout=600.0)
                continue
            try:
                _MISSES.inc(kind=key.kind)
                if exportable:
                    loaded = _load_artifact(key, arg_specs)
                    if loaded is not None:
                        fn, stats = loaded
                        _ARTIFACT_HITS.inc(kind=key.kind)
                        # header stats win (they survive even when the
                        # loaded module was not XLA-compiled yet); insert
                        # re-analyzes only when the header carried none
                        return self.insert(key, fn, source="artifact",
                                           stats=stats)
                fn, extras, exported = build()
                entry = self.insert(key, fn, extras, source="build")
                if exportable and exported is not None:
                    _write_artifact(key, exported, stats=entry.stats)
                return entry
            finally:
                with self._lock:
                    self._building.pop(key, None)
                event.set()


CACHE = AOTCache()


def compile_cached(key, build, exportable=False, arg_specs=None):
    """THE module entry point every hot path dispatches through (jit.py
    TrainStep/EvalStep, contrib.serving.ServedModel, serving prewarm).
    ``build()`` is traced/compiled on a miss — the same retrace-hazard
    surface as a direct ``jax.jit`` call site (mxtpulint R011 models this
    boundary). Returns the cache entry (``entry.fn`` is the compiled
    program, ``entry.source`` says whether it came from a build or a
    persisted artifact)."""
    return CACHE.get_or_build(key, build, exportable=exportable,
                              arg_specs=arg_specs)


def _bucket_of(key):
    """Batch-bucket label for the program gauges: dim 0 of the first
    input (the batcher's bucket axis), '-' for rank-0/inputless keys."""
    try:
        return int(key.input_sig[0][0][0])
    except Exception:
        return "-"


def _publish_program_stats(key, stats):
    """Mirror one entry's device truth onto the program gauges. Guarded:
    a telemetry failure must not fail the build/load that produced the
    executable."""
    try:
        bucket = _bucket_of(key)
        _PROG_FLOPS.set(stats.get("flops", 0.0), model=key.model_id,
                        kind=key.kind, bucket=bucket)
        _PROG_PEAK_BYTES.set(stats.get("peak_bytes", 0.0),
                             model=key.model_id, kind=key.kind,
                             bucket=bucket)
    except Exception:
        _LOG.debug("program stats gauge update dropped", exc_info=True)


# --------------------------------------------------------------------------
# Persistent artifact layer (MXTPU_AOT_CACHE_DIR)
def _key_digest(key):
    raw = repr((FORMAT_VERSION, key.model_id, key.kind, key.input_sig,
                key.mesh, key.extra))
    return hashlib.sha256(raw.encode()).hexdigest()[:32]


def artifact_path(key, cache_dir=None):
    """Artifact file for a key, or None when the layer is disabled
    (no MXTPU_AOT_CACHE_DIR) or the key is not persistable (train
    programs stay in-memory).

    Mesh-sharded eval/serve programs ARE persisted: jax.export records
    the partitioned module (GSPMD shardings included), and the key's
    ``mesh`` signature — axis layout + device count — participates in the
    file digest, so a process with a different topology can never load a
    mismatched partitioning (it misses and rebuilds). This is the
    sharded-serving counterpart of the single-device zero-retrace
    cold start (docs/AOT.md "Sharded artifacts")."""
    if cache_dir is None:
        cache_dir = config.get_env("MXTPU_AOT_CACHE_DIR")
    # train programs are NEVER persisted (donated buffers + instance-bound
    # state) — enforced here, not just at today's call sites
    if not cache_dir or key.kind == "train":
        return None
    import jax
    return os.path.join(cache_dir, "jax-%s" % jax.__version__,
                        "%s-%s.mxtpu-aot" % (key.kind, _key_digest(key)))


def _pack_header(stats):
    """v2 header: 4-byte big-endian length + JSON metadata. The metadata
    carries the program's device truth so a fresh process's artifact load
    never needs to re-run XLA analysis to know its FLOPs."""
    meta = _json.dumps({"format": FORMAT_VERSION,
                        "stats": stats if stats else None},
                       sort_keys=True).encode("utf-8")
    return struct.pack(">I", len(meta)) + meta


def _unpack_header(buf):
    """(stats_or_None, payload_offset) for a v2 body (magic stripped).
    Raises on truncation/garbage — the caller treats that as a corrupt
    artifact and rebuilds."""
    if len(buf) < 4:
        raise ValueError("truncated artifact header")
    (n,) = struct.unpack(">I", buf[:4])
    if n > len(buf) - 4:
        raise ValueError("artifact header length %d overruns file" % n)
    meta = _json.loads(buf[4:4 + n].decode("utf-8"))
    stats = meta.get("stats") if isinstance(meta, dict) else None
    if stats is not None and not isinstance(stats, dict):
        stats = None
    return stats, 4 + n


def _load_artifact(key, arg_specs):
    """Deserialize the persisted StableHLO for ``key`` and AOT-compile it
    (``aot:load`` span). Returns ``(compiled, stats)`` — the header's
    device truth rides along — or None (missing / corrupt / wrong-version
    magic / unloadable: the caller falls back to a fresh build WITH
    re-analysis; the drop is debug-logged, never raised into a hot
    path)."""
    path = artifact_path(key)
    if path is None or not os.path.exists(path):
        return None
    try:
        # faultlab site "aot.artifact_read": artifact_corrupt injects an
        # unreadable artifact (identical to the real corrupt path — the
        # caller rebuilds with re-analysis); exception-kind lands in the
        # except-all below, exercising the same fallback
        if faultlab.armed and faultlab.fire(
                "aot.artifact_read", kind=key.kind,
                model_id=key.model_id) == "artifact_corrupt":
            _LOG.debug("aot artifact read for %s: injected corrupt", path)
            return None
        import jax
        import jax.export  # jax>=0.4.30 does not re-export lazily
        with open(path, "rb") as f:
            buf = f.read()
        if not buf.startswith(ARTIFACT_MAGIC):
            # wrong magic OR an old format version (the version byte is
            # part of the magic): rebuild + re-analyze, never misparse
            raise ValueError("bad magic/version in %s" % path)
        stats, off = _unpack_header(buf[len(ARTIFACT_MAGIC):])
        with spans.span("aot:load", kind=key.kind,
                        model_id=key.model_id):
            exported = jax.export.deserialize(
                buf[len(ARTIFACT_MAGIC) + off:])
            fn = jax.jit(exported.call)
            if arg_specs is not None:
                # explicit AOT: XLA-compile the loaded module NOW (inside
                # the aot:load span / prewarm window) — never lazily
                # inside a later dispatch
                fn = fn.lower(*arg_specs).compile()
        return fn, stats
    except Exception:
        _LOG.debug("aot artifact load failed for %s", path, exc_info=True)
        return None


def _write_artifact(key, exported, stats=None):
    """Persist a jax.export program atomically (tmp + rename; pid+tid in
    the tmp name so concurrent writers never interleave), with the
    program's device truth in the v2 header. Failures are debug-logged
    and swallowed — a full disk must not fail the dispatch that just
    compiled successfully."""
    path = artifact_path(key)
    if path is None:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = "%s.%d.%d.tmp" % (path, os.getpid(), threading.get_ident())
        with open(tmp, "wb") as f:
            f.write(ARTIFACT_MAGIC + _pack_header(stats)
                    + exported.serialize())
        os.replace(tmp, path)
        _ARTIFACT_WRITES.inc(kind=key.kind)
        return path
    except Exception:
        _LOG.debug("aot artifact write failed for %s", path, exc_info=True)
        return None


# --------------------------------------------------------------------------
# Per-program fact digests (the hlodiff contract)
#
# ``program_digest`` is the stable identity of one artifact's BYTES (magic
# + header + payload): two byte-identical deploys share it, so the
# differential analyzer (tools/hlodiff) can prove "empty diff" without
# walking either module. ``facts_for_key`` resolves a cache key to the
# persisted artifact's header facts + digest WITHOUT deserializing the
# payload — the differ and any future planner cost model read device
# truth from here instead of re-deriving the header parsing.

#: (path, digest, stats): one persisted program's identity + header
#: device truth. ``digest`` is program_digest of the file bytes; ``stats``
#: is the v2 header dict ({flops, bytes_accessed, peak_bytes,
#: output_bytes}) or None for statless artifacts.
ProgramFactsRef = namedtuple("ProgramFactsRef", ("path", "digest", "stats"))

_FACTS_MEMO = {}                  # path -> (mtime_ns, size, ProgramFactsRef)
_FACTS_MEMO_LOCK = threading.Lock()
_FACTS_MEMO_MAX = 512


def program_digest(buf):
    """Stable digest of one artifact's full bytes — the same 32-hex-char
    width as the cache-key digest in the filename, but content-addressed:
    it changes iff the deployed bytes change."""
    return hashlib.sha256(bytes(buf)).hexdigest()[:32]


def facts_for_key(key, cache_dir=None):
    """Header facts for the persisted artifact of ``key`` ->
    ``ProgramFactsRef(path, digest, stats)``, or None when the key has no
    readable artifact (train kind, disabled layer, missing/corrupt file).
    Reads magic + header only — never the jax.export payload — and memos
    per (path, mtime, size), so a gate that re-checks the routed
    version's facts on every deploy costs one ``stat()``."""
    path = artifact_path(key, cache_dir)
    if path is None:
        return None
    try:
        st = os.stat(path)
    except OSError:
        return None
    with _FACTS_MEMO_LOCK:
        memo = _FACTS_MEMO.get(path)
        if memo is not None and memo[0] == st.st_mtime_ns \
                and memo[1] == st.st_size:
            return memo[2]
    try:
        with open(path, "rb") as f:
            buf = f.read()
        if not buf.startswith(ARTIFACT_MAGIC):
            return None
        stats, _off = _unpack_header(buf[len(ARTIFACT_MAGIC):])
    except Exception:
        _LOG.debug("aot facts_for_key failed for %s", path, exc_info=True)
        return None
    ref = ProgramFactsRef(path, program_digest(buf), stats)
    with _FACTS_MEMO_LOCK:
        if len(_FACTS_MEMO) >= _FACTS_MEMO_MAX:
            _FACTS_MEMO.clear()
        _FACTS_MEMO[path] = (st.st_mtime_ns, st.st_size, ref)
    return ref
