"""Bucketed sequence iterator (ref python/mxnet/rnn/io.py
BucketSentenceIter, encode_sentences) for BucketingModule training."""
from __future__ import annotations

import random as pyrandom

import numpy as onp

from .. import ndarray as nd
from ..io.io import DataIter, DataBatch, DataDesc

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1, invalid_key="\n",
                     start_label=0):
    """Map token sequences to int ids, building vocab on the fly
    (ref io.py encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                assert new_vocab, "Unknown token %s" % word
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Pads each sentence to its bucket length; label is data shifted left
    by one (ref io.py BucketSentenceIter)."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            counts = onp.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(counts)
                       if n >= batch_size]
        buckets.sort()
        self.buckets = buckets
        self.data = [[] for _ in buckets]
        ndiscard = 0
        for sent in sentences:
            buck = next((i for i, b in enumerate(buckets) if b >= len(sent)),
                        None)
            if buck is None:
                ndiscard += 1
                continue
            buff = onp.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[: len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [onp.asarray(x, dtype=dtype) for x in self.data]
        if ndiscard:
            import logging
            logging.warning("discarded %d sentences longer than the largest "
                            "bucket", ndiscard)
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.major_axis = layout.find("N")
        self.default_bucket_key = max(buckets)
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend((i, j) for j in
                            range(0, len(buck) - batch_size + 1, batch_size))
        self.curr_idx = 0
        self.reset()

    @property
    def provide_data(self):
        shape = (self.batch_size, self.default_bucket_key) \
            if self.major_axis == 0 else (self.default_bucket_key, self.batch_size)
        return [DataDesc(self.data_name, shape, self.dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size, self.default_bucket_key) \
            if self.major_axis == 0 else (self.default_bucket_key, self.batch_size)
        return [DataDesc(self.label_name, shape, self.dtype)]

    def reset(self):
        self.curr_idx = 0
        pyrandom.shuffle(self.idx)
        for buck in self.data:
            onp.random.shuffle(buck)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = onp.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j: j + self.batch_size]
        label = self.ndlabel[i][j: j + self.batch_size]
        if self.major_axis == 1:
            data, label = data.T, label.T
        batch = DataBatch(nd.array(data), nd.array(label),
                          pad=0, bucket_key=self.buckets[i],
                          provide_data=[DataDesc(self.data_name, data.shape,
                                                 self.dtype)],
                          provide_label=[DataDesc(self.label_name, label.shape,
                                                  self.dtype)])
        return batch
