"""RNN checkpoint helpers (ref python/mxnet/rnn/rnn.py).

The reference packs/unpacks fused cuDNN parameter blobs around
save/load_checkpoint; our cells keep weights unfused (one named array per
gate matrix — see rnn_cell.py FusedRNNCell docstring), so pack/unpack are
identity and these reduce to the plain model checkpoint with cell-aware
round-tripping.
"""
from __future__ import annotations

from .. import model as _model

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _cells_list(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """ref rnn.py save_rnn_checkpoint."""
    for cell in _cells_list(cells):
        arg_params = cell.unpack_weights(arg_params)
    _model.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """ref rnn.py load_rnn_checkpoint."""
    sym, arg, aux = _model.load_checkpoint(prefix, epoch)
    for cell in _cells_list(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback (ref rnn.py do_rnn_checkpoint)."""
    period = max(1, period)

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
