"""Legacy symbolic RNN cell API (ref python/mxnet/rnn/rnn_cell.py) — cells
that BUILD Symbol graphs, for the Module/BucketingModule training path.

TPU-native: each unrolled step is plain Symbol composition; the bound
executor compiles the whole unrolled sequence as one XLA program, so the
reference's fused-kernel distinction (FusedRNNCell = cuDNN) collapses —
FusedRNNCell here is a stacked/bidirectional composition with the same
parameter sharing, and unfuse() returns the equivalent explicit stack.
"""
from __future__ import annotations

from .. import symbol as sym
from ..symbol.symbol import Symbol, _auto_name

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams(object):
    """Container for cell weights (ref rnn_cell.py RNNParams): one shared
    namespace so stacked/bidirectional cells reuse variables by name."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.var(name, **kwargs)
        return self._params[name]


def _begin_state_op(d, num_hidden=0):
    """(batch_of(d), num_hidden) zeros — registered so graph JSON reloads
    (symbol.load_json resolves ops by name through _OP_TABLE)."""
    from .. import ndarray as nd
    return nd.zeros((d.shape[0], num_hidden), dtype=d.dtype)


def _register_begin_state():
    from ..symbol import _OP_TABLE
    _OP_TABLE.setdefault("_begin_state", _begin_state_op)


_register_begin_state()


def _zeros_like_batch(x, num_hidden, name):
    """Deferred zero state: (batch_of(x), num_hidden) materialized at bind
    time — the symbolic analog of begin_state's runtime batch size."""
    return Symbol(op=_begin_state_op, op_name="_begin_state", inputs=[x],
                  kwargs={"num_hidden": num_hidden}, name=name)


class BaseRNNCell(object):
    """ref rnn_cell.py BaseRNNCell."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [s["shape"] for s in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    def begin_state(self, func=None, like=None, **kwargs):
        """States for step 0. With `like` (a data Symbol) the batch dim is
        deferred to bind; otherwise func/kwargs must fix a concrete shape
        (func=sym.zeros, batch_size=N)."""
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if like is not None:
                states.append(_zeros_like_batch(like, info["shape"][1], name))
            elif func is not None:
                shape = (kwargs.get("batch_size", 0),) + tuple(info["shape"][1:])
                assert shape[0] > 0, \
                    "begin_state without `like` needs batch_size > 0"
                states.append(func(shape))
            else:
                states.append(sym.var(name))
        return states

    def unpack_weights(self, args):
        """ref unpack_weights — our cells keep weights unfused already."""
        return dict(args)

    def pack_weights(self, args):
        return dict(args)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """ref rnn_cell.py unroll: inputs is a (N,T,C) Symbol (layout NTC),
        a (T,N,C) Symbol (TNC), or a list of T (N,C) Symbols."""
        self.reset()
        axis = layout.find("T")
        if isinstance(inputs, (list, tuple)):
            seq = list(inputs)
        else:
            seq = []
            for t in range(length):
                s = sym.slice_axis(inputs, axis=axis, begin=t, end=t + 1)
                seq.append(sym.squeeze(s, axis=axis))
        states = begin_state if begin_state is not None \
            else self.begin_state(like=seq[0])
        outputs = []
        for t in range(length):
            out, states = self(seq[t], states)
            outputs.append(out)
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, states


def _defer(v, shape_fn):
    """Mark a cell weight for bind-time shape inference (executor.py:102
    materializes it from the consuming op's data shape)."""
    if not hasattr(v, "_deferred_shape_fn"):
        v._deferred_shape_fn = shape_fn
        v._is_param = True
    return v


class RNNCell(BaseRNNCell):
    """Elman cell on symbols (ref rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_", params=None):
        super().__init__(prefix, params)
        n = num_hidden
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = _defer(self.params.get("i2h_weight"), lambda s: (n, s[-1]))
        self._iB = _defer(self.params.get("i2h_bias"), lambda s: (n,))
        self._hW = _defer(self.params.get("h2h_weight"), lambda s: (n, n))
        self._hB = _defer(self.params.get("h2h_bias"), lambda s: (n,))

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden, flatten=False,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden, flatten=False,
                                 name="%sh2h" % name)
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM on symbols, i,f,g,o gate order (ref rnn_cell.py LSTMCell)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix, params)
        n = num_hidden
        self._num_hidden = num_hidden
        # forget_bias is recorded as a var attr (ref LSTMBias initializer);
        # name-based initializers set biases to zeros, so training starts
        # with forget gates at sigmoid(0) unless the user re-inits
        self._iW = _defer(self.params.get("i2h_weight"), lambda s: (4 * n, s[-1]))
        self._iB = _defer(self.params.get("i2h_bias", __forget_bias__=forget_bias),
                          lambda s: (4 * n,))
        self._hW = _defer(self.params.get("h2h_weight"), lambda s: (4 * n, n))
        self._hB = _defer(self.params.get("h2h_bias"), lambda s: (4 * n,))

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        n = self._num_hidden
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=4 * n, flatten=False,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=4 * n, flatten=False,
                                 name="%sh2h" % name)
        gates = i2h + h2h
        ig = sym.sigmoid(sym.slice_axis(gates, axis=-1, begin=0, end=n))
        fg = sym.sigmoid(sym.slice_axis(gates, axis=-1, begin=n, end=2 * n))
        gg = sym.tanh(sym.slice_axis(gates, axis=-1, begin=2 * n, end=3 * n))
        og = sym.sigmoid(sym.slice_axis(gates, axis=-1, begin=3 * n, end=4 * n))
        next_c = fg * states[1] + ig * gg
        next_h = og * sym.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU on symbols (ref rnn_cell.py GRUCell)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix, params)
        n = num_hidden
        self._num_hidden = num_hidden
        self._iW = _defer(self.params.get("i2h_weight"), lambda s: (3 * n, s[-1]))
        self._iB = _defer(self.params.get("i2h_bias"), lambda s: (3 * n,))
        self._hW = _defer(self.params.get("h2h_weight"), lambda s: (3 * n, n))
        self._hB = _defer(self.params.get("h2h_bias"), lambda s: (3 * n,))

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        n = self._num_hidden
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=3 * n, flatten=False,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=3 * n, flatten=False,
                                 name="%sh2h" % name)
        ir = sym.slice_axis(i2h, axis=-1, begin=0, end=n)
        iz = sym.slice_axis(i2h, axis=-1, begin=n, end=2 * n)
        in_ = sym.slice_axis(i2h, axis=-1, begin=2 * n, end=3 * n)
        hr = sym.slice_axis(h2h, axis=-1, begin=0, end=n)
        hz = sym.slice_axis(h2h, axis=-1, begin=n, end=2 * n)
        hn = sym.slice_axis(h2h, axis=-1, begin=2 * n, end=3 * n)
        reset = sym.sigmoid(ir + hr)
        update = sym.sigmoid(iz + hz)
        newmem = sym.tanh(in_ + reset * hn)
        out = (sym.ones_like(update) - update) * newmem + update * states[0]
        return out, [out]


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells (ref rnn_cell.py SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, **kwargs):
        return sum((c.begin_state(**kwargs) for c in self._cells), [])

    def __call__(self, inputs, states):
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            inputs, st = cell(inputs, states[p:p + n])
            next_states.extend(st)
            p += n
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = None if begin_state is None else begin_state[p:p + n]
            inputs, st = cell.unroll(
                length, inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(st)
            p += n
        return inputs, next_states

    def reset(self):
        super().reset()
        for c in self._cells:
            c.reset()


class BidirectionalCell(BaseRNNCell):
    """l/r cells over the sequence both ways, outputs concatenated
    (ref rnn_cell.py BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__("", params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l_cell.state_info + self._r_cell.state_info

    def begin_state(self, **kwargs):
        return self._l_cell.begin_state(**kwargs) + \
            self._r_cell.begin_state(**kwargs)

    def __call__(self, inputs, states):
        raise NotImplementedError("BidirectionalCell can only be unrolled")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if not isinstance(inputs, (list, tuple)):
            seq = [sym.squeeze(sym.slice_axis(inputs, axis=axis, begin=t,
                                              end=t + 1), axis=axis)
                   for t in range(length)]
        else:
            seq = list(inputs)
        nl = len(self._l_cell.state_info)
        lst = None if begin_state is None else begin_state[:nl]
        rst = None if begin_state is None else begin_state[nl:]
        l_out, l_states = self._l_cell.unroll(length, seq, begin_state=lst,
                                              layout=layout, merge_outputs=None)
        r_out, r_states = self._r_cell.unroll(length, list(reversed(seq)),
                                              begin_state=rst, layout=layout,
                                              merge_outputs=None)
        r_out = list(reversed(r_out))
        outputs = [sym.concat(l, r, dim=-1,
                              name="%st%d" % (self._output_prefix, t))
                   for t, (l, r) in enumerate(zip(l_out, r_out))]
        if merge_outputs:
            outputs = sym.stack(*outputs, axis=axis)
        return outputs, l_states + r_states

    def reset(self):
        super().reset()
        self._l_cell.reset()
        self._r_cell.reset()


class FusedRNNCell(BaseRNNCell):
    """ref rnn_cell.py FusedRNNCell (the cuDNN path). On TPU the unrolled
    graph compiles to one XLA program either way, so this is the stacked
    (optionally bidirectional) composition with fused-style naming;
    unfuse() returns the explicit SequentialRNNCell."""

    _MODES = {"rnn_relu": (RNNCell, {"activation": "relu"}),
              "rnn_tanh": (RNNCell, {"activation": "tanh"}),
              "lstm": (LSTMCell, {}),
              "gru": (GRUCell, {})}

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, prefix=None, params=None):
        if mode not in self._MODES:
            raise ValueError("mode must be one of %s" % list(self._MODES))
        prefix = prefix if prefix is not None else "%s_" % mode
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._stack = self.unfuse()

    def unfuse(self):
        cls, kw = self._MODES[self._mode]
        stack = SequentialRNNCell()
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    cls(self._num_hidden, prefix="%sl%d_" % (self._prefix, i),
                        **kw),
                    cls(self._num_hidden, prefix="%sr%d_" % (self._prefix, i),
                        **kw),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(cls(self._num_hidden,
                              prefix="%sl%d_" % (self._prefix, i), **kw))
            if self._dropout > 0 and i < self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_" % (self._prefix, i)))
        return stack

    @property
    def state_info(self):
        return self._stack.state_info

    def begin_state(self, **kwargs):
        return self._stack.begin_state(**kwargs)

    def __call__(self, inputs, states):
        return self._stack(inputs, states)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        return self._stack.unroll(length, inputs, begin_state=begin_state,
                                  layout=layout, merge_outputs=merge_outputs)


class DropoutCell(BaseRNNCell):
    """ref rnn_cell.py DropoutCell."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = sym.Dropout(inputs, p=self._dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """ref rnn_cell.py ModifierCell."""

    def __init__(self, base_cell):
        super().__init__(prefix="", params=None)
        self.base_cell = base_cell

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        return self.base_cell.begin_state(**kwargs)

    def reset(self):
        super().reset()
        self.base_cell.reset()


class ZoneoutCell(ModifierCell):
    """ref rnn_cell.py ZoneoutCell: randomly keep previous output."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        if self.zoneout_outputs > 0:
            prev = self._prev_output if self._prev_output is not None \
                else sym.zeros_like(out)
            mask = sym.Dropout(sym.ones_like(out), p=self.zoneout_outputs)
            # Dropout scales by 1/(1-p): renormalize to a 0/1 keep mask
            keep = sym.minimum(mask, sym.ones_like(mask))
            out = keep * out + (sym.ones_like(keep) - keep) * prev
        self._prev_output = out
        return out, next_states


class ResidualCell(ModifierCell):
    """ref rnn_cell.py ResidualCell: output += input."""

    def __call__(self, inputs, states):
        out, next_states = self.base_cell(inputs, states)
        return out + inputs, next_states
