"""Legacy symbolic RNN API — mx.rnn (ref python/mxnet/rnn/)."""
from .rnn_cell import *  # noqa
from .io import *  # noqa
from .rnn import *  # noqa

from . import rnn_cell, io, rnn  # noqa

__all__ = rnn_cell.__all__ + io.__all__ + rnn.__all__
