"""Optimizer API (ref python/mxnet/optimizer/__init__.py)."""
from .optimizer import *  # noqa
from .optimizer import Optimizer, create, register, Updater, get_updater  # noqa
