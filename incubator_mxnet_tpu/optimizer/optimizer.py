"""Optimizers (ref python/mxnet/optimizer/optimizer.py + src/operator/optimizer_op.cc).

Reference parity: the 17-optimizer registry, ``create_state``,
``update_multi_precision`` (fp32 master weights for low-precision params),
lr/wd multipliers, rescale_grad and clip_gradient.

TPU-native design: each update rule is a pure JAX function; the eager path
applies it per-parameter (XLA-compiled, cached), while the jitted train-step
path (gluon.Trainer hybridized / module fast path) fuses ALL parameter updates
into the single compiled step program with donated buffers — the analog of the
reference's fused ``multi_sgd``-style update-as-op design.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as onp

from ..base import registry
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["Optimizer", "SGD", "Adam", "AdamW", "NAG", "RMSProp", "AdaGrad", "AdaDelta",
           "Adamax", "Nadam", "Ftrl", "FTML", "LAMB", "LARS", "Signum", "SGLD", "DCASGD",
           "Test", "create", "register", "Updater", "get_updater"]

_REG = registry("optimizer")


def register(klass):
    return _REG.register(klass)


class Optimizer:
    """Base optimizer (ref optimizer.py:29)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0, clip_gradient=None,
                 learning_rate=0.01, lr_scheduler=None, sym=None, begin_num_update=0,
                 multi_precision=False, param_dict=None, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.sym_info = ((sym.attr_dict(), sym.list_arguments())
                         if sym is not None else ())
        self._states = {}
        # the reference __init__ applies __lr_mult__/__wd_mult__ attributes
        # immediately (ref optimizer.py:139-140)
        self.set_lr_mult({})
        self.set_wd_mult({})

    # -- registry ------------------------------------------------------
    @staticmethod
    def create_optimizer(name, **kwargs):
        return _REG.create(name, **kwargs)

    # -- state ---------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and weight.dtype in (jnp.bfloat16, onp.float16):
            master = weight.astype("float32")
            return (master, self.create_state(index, master))
        return self.create_state(index, weight)

    # -- schedules -----------------------------------------------------
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler is not None else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def set_lr_mult(self, args_lr_mult):
        """Per-parameter lr multipliers; honors __lr_mult__ symbol attributes
        (ref optimizer.py:372-402)."""
        self.lr_mult = {}
        if getattr(self, "sym_info", None):
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """Per-parameter weight-decay multipliers (ref optimizer.py:404-431).

        Matches the reference exactly: only ``__wd_mult__`` symbol attributes
        (when sym_info is available) plus the user-supplied dict are applied;
        biases/gamma/beta are NOT auto-excluded from weight decay (the
        reference decays them too)."""
        self.wd_mult = {}
        if getattr(self, "sym_info", None):
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("lr_scheduler is set; cannot set learning rate directly")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    # -- the update rule (pure function; override in subclasses) -------
    def update_rule(self, weight, grad, state, lr, wd, t):
        """Pure: (w, g, state, lr, wd, step) -> (new_w, new_state)."""
        raise NotImplementedError

    def _preprocess_grad(self, grad):
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    # -- eager entry points (kvstore/Trainer call these) ---------------
    def update(self, index, weight, grad, state):
        from ..ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            # row-sparse (lazy) update: touch only the rows the grad carries
            # (ref optimizer_op.cc sgd_update row_sparse kernels / the
            # sparse-embedding training path). Rules with dense state
            # semantics fall back to densifying the grad.
            if self._sparse_lazy_supported(state):
                return self._sparse_lazy_update(index, weight, grad, state)
            grad = grad.tostype("default")
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        g = self._preprocess_grad(grad._data).astype(jnp.float32)
        w = weight._data
        new_w, new_state = self.update_rule(w.astype(jnp.float32), g, state, lr, wd, t)
        weight._data = new_w.astype(w.dtype)
        return new_state

    def _sparse_lazy_supported(self, state):
        return False

    def _sparse_lazy_update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        """fp32 master-weight update for bf16/fp16 params (ref optimizer.py:320)."""
        from ..ndarray.sparse import RowSparseNDArray
        if self.multi_precision and weight.dtype in (jnp.bfloat16, onp.float16):
            if isinstance(grad, RowSparseNDArray):
                grad = grad.tostype("default")  # master-weight flow is dense
            master, inner = state
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            t = self._index_update_count[index]
            g = self._preprocess_grad(grad._data).astype(jnp.float32)
            new_master, new_inner = self.update_rule(master._data, g, inner, lr, wd, t)
            master._data = new_master
            weight._data = new_master.astype(weight.dtype)
            return (master, new_inner)
        return self.update(index, weight, grad, state)


@register
class Test(Optimizer):
    def create_state(self, index, weight):
        return nd.zeros(weight.shape, dtype=weight.dtype)

    def update_rule(self, w, g, state, lr, wd, t):
        return w + g * self.rescale_grad, state


@register
class SGD(Optimizer):
    """SGD w/ momentum (ref src/operator/optimizer_op.cc sgd_mom_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        # lazy_update default True matches the reference (optimizer.py SGD):
        # row_sparse grads touch only the rows they carry unless disabled
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, jnp.float32))

    def update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if state is None:
            return w - lr * g, None
        mom = self.momentum * state._data - lr * g
        state._data = mom
        return w + mom, state

    def _sparse_lazy_supported(self, state):
        return self.lazy_update

    def _sparse_lazy_update(self, index, weight, grad, state):
        """Row-sparse SGD: only rows in grad.indices are touched — weight AND
        momentum (the reference's lazy_update semantics)."""
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        idx = grad.indices._data
        g = self._preprocess_grad(grad.data._data).astype(jnp.float32)
        w = weight._data
        rows = w[idx].astype(jnp.float32)
        g = g + wd * rows
        if state is None:
            new_rows = rows - lr * g
        else:
            mom_rows = self.momentum * state._data[idx] - lr * g
            state._data = state._data.at[idx].set(mom_rows)
            new_rows = rows + mom_rows
        weight._data = w.at[idx].set(new_rows.astype(w.dtype))
        return state


@register
class NAG(SGD):
    """Nesterov (ref optimizer.py NAG / nag_mom_update)."""

    def _sparse_lazy_supported(self, state):
        return False  # Nesterov lookahead has no lazy-row formulation here

    def update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if state is None:
            return w - lr * g, None
        mom = self.momentum * state._data + g
        state._data = mom
        return w - lr * (g + self.momentum * mom), state


@register
class Signum(Optimizer):
    """signSGD w/ momentum (ref optimizer.py Signum / signum_update)."""

    def __init__(self, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return NDArray(jnp.zeros(weight.shape, jnp.float32))

    def update_rule(self, w, g, state, lr, wd, t):
        if state is None:
            return w * (1 - lr * self.wd_lh) - lr * jnp.sign(g + wd * w), None
        mom = self.momentum * state._data - (1 - self.momentum) * (g + wd * w)
        state._data = mom
        return w * (1 - lr * self.wd_lh) + lr * jnp.sign(mom), state


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref optimizer.py SGLD)."""

    def update_rule(self, w, g, state, lr, wd, t):
        from ..ndarray import random as _rnd
        noise = jax.random.normal(_rnd._next_key(), w.shape, w.dtype) * jnp.sqrt(lr)
        return w - lr / 2 * (g + wd * w) + noise, state


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros(weight.shape, jnp.float32)),
                NDArray(jnp.array(weight._data, jnp.float32)))

    def update_rule(self, w, g, state, lr, wd, t):
        mom, prev_w = state
        m = self.momentum * mom._data - lr * (
            g + wd * w + self.lamda * g * g * (w - prev_w._data))
        mom._data = m
        prev_w._data = w + m
        return w + m, state


@register
class Adam(Optimizer):
    """ref optimizer.py Adam / adam_update."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros(weight.shape, jnp.float32)),
                NDArray(jnp.zeros(weight.shape, jnp.float32)))

    def update_rule(self, w, g, state, lr, wd, t):
        m, v = state
        g = g + wd * w
        lr_t = lr * jnp.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        v._data = self.beta2 * v._data + (1 - self.beta2) * g * g
        return w - lr_t * m._data / (jnp.sqrt(v._data) + self.epsilon), state


@register
class AdamW(Adam):
    """Decoupled weight decay (GluonNLP-style bertadam/adamw)."""

    def update_rule(self, w, g, state, lr, wd, t):
        m, v = state
        lr_t = lr * jnp.sqrt(1 - self.beta2 ** t) / (1 - self.beta1 ** t)
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        v._data = self.beta2 * v._data + (1 - self.beta2) * g * g
        return w - lr_t * (m._data / (jnp.sqrt(v._data) + self.epsilon) + wd * w), state


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros(weight.shape, jnp.float32)),
                NDArray(jnp.zeros(weight.shape, jnp.float32)))

    def update_rule(self, w, g, state, lr, wd, t):
        m, u = state
        g = g + wd * w
        lr_t = lr / (1 - self.beta1 ** t)
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        u._data = jnp.maximum(self.beta2 * u._data, jnp.abs(g))
        return w - lr_t * m._data / (u._data + 1e-8), state


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros(weight.shape, jnp.float32)),
                NDArray(jnp.zeros(weight.shape, jnp.float32)))

    def update_rule(self, w, g, state, lr, wd, t):
        m, v = state
        g = g + wd * w
        momentum_t = self.beta1 * (1 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t1 = self.beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_sched_next = self.m_schedule * momentum_t1
        g_prime = g / (1 - self.m_schedule)
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        v._data = self.beta2 * v._data + (1 - self.beta2) * g * g
        m_prime = m._data / (1 - m_sched_next)
        v_prime = v._data / (1 - self.beta2 ** t)
        m_bar = (1 - momentum_t) * g_prime + momentum_t1 * m_prime
        return w - lr * m_bar / (jnp.sqrt(v_prime) + self.epsilon), state


@register
class RMSProp(Optimizer):
    """ref optimizer.py RMSProp (centered variant = Graves)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2, self.epsilon = gamma1, gamma2, epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, jnp.float32))
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        if self.centered:
            n, gm, delta = state
            n._data = (1 - self.gamma1) * g * g + self.gamma1 * n._data
            gm._data = (1 - self.gamma1) * g + self.gamma1 * gm._data
            delta._data = self.gamma2 * delta._data - lr * g / jnp.sqrt(
                n._data - gm._data * gm._data + self.epsilon)
            w = w + delta._data
        else:
            (n,) = state
            n._data = (1 - self.gamma1) * g * g + self.gamma1 * n._data
            w = w - lr * g / jnp.sqrt(n._data + self.epsilon)
        if self.clip_weights:
            w = jnp.clip(w, -self.clip_weights, self.clip_weights)
        return w, state


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return NDArray(jnp.zeros(weight.shape, jnp.float32))

    def update_rule(self, w, g, state, lr, wd, t):
        g = g + wd * w
        state._data = state._data + g * g
        return w - lr * g / jnp.sqrt(state._data + self.float_stable_eps), state


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.9, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros(weight.shape, jnp.float32)),
                NDArray(jnp.zeros(weight.shape, jnp.float32)))

    def update_rule(self, w, g, state, lr, wd, t):
        acc_g, acc_delta = state
        g = g + wd * w
        acc_g._data = self.rho * acc_g._data + (1 - self.rho) * g * g
        delta = jnp.sqrt(acc_delta._data + self.epsilon) / jnp.sqrt(acc_g._data + self.epsilon) * g
        acc_delta._data = self.rho * acc_delta._data + (1 - self.rho) * delta * delta
        return w - delta, state


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros(weight.shape, jnp.float32)),
                NDArray(jnp.zeros(weight.shape, jnp.float32)))

    def update_rule(self, w, g, state, lr, wd, t):
        z, n = state
        sigma = (jnp.sqrt(n._data + g * g) - jnp.sqrt(n._data)) / lr
        z._data = z._data + g - sigma * w
        n._data = n._data + g * g
        new_w = (jnp.sign(z._data) * self.lamda1 - z._data) / (
            (self.beta + jnp.sqrt(n._data)) / lr + wd) * (jnp.abs(z._data) > self.lamda1)
        return new_w, state


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = lambda: NDArray(jnp.zeros(weight.shape, jnp.float32))
        return (z(), z(), z())

    def update_rule(self, w, g, state, lr, wd, t):
        d, v, z = state
        g = g + wd * w
        v._data = self.beta2 * v._data + (1 - self.beta2) * g * g
        d_t = (1 - self.beta1 ** t) / lr * (
            jnp.sqrt(v._data / (1 - self.beta2 ** t)) + self.epsilon)
        sigma = d_t - self.beta1 * d._data
        z._data = self.beta1 * z._data + (1 - self.beta1) * g - sigma * w
        d._data = d_t
        return -z._data / d_t, state


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments (ref optimizer.py LAMB / lamb_update_phase1/2)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6,
                 lower_bound=None, upper_bound=None, bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (NDArray(jnp.zeros(weight.shape, jnp.float32)),
                NDArray(jnp.zeros(weight.shape, jnp.float32)))

    def update_rule(self, w, g, state, lr, wd, t):
        m, v = state
        m._data = self.beta1 * m._data + (1 - self.beta1) * g
        v._data = self.beta2 * v._data + (1 - self.beta2) * g * g
        mh, vh = m._data, v._data
        if self.bias_correction:
            mh = mh / (1 - self.beta1 ** t)
            vh = vh / (1 - self.beta2 ** t)
        r = mh / (jnp.sqrt(vh) + self.epsilon) + wd * w
        w_norm = jnp.linalg.norm(w)
        if self.lower_bound:
            w_norm = jnp.maximum(w_norm, self.lower_bound)
        if self.upper_bound:
            w_norm = jnp.minimum(w_norm, self.upper_bound)
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return w - lr * ratio * r, state


@register
class LARS(SGD):
    """Layer-wise adaptive rate scaling (ref optimizer.py LARS)."""

    def __init__(self, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.eta, self.epsilon = eta, epsilon

    def update_rule(self, w, g, state, lr, wd, t):
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where((w_norm > 0) & (g_norm > 0),
                          self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon), 1.0)
        return super().update_rule(w, g * trust, state, lr, wd, t)


def create(name, **kwargs):
    return _REG.create(name, **kwargs)


class Updater:
    """KVStore server-side updater (ref python/mxnet/optimizer/updater.py)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
        new_state = self.optimizer.update_multi_precision(
            index, weight, grad, self.states[index])
        # explicit None check: `or` would call __bool__ on an NDArray state
        # (e.g. SGD momentum buffers) and raise on >1 element
        if new_state is not None:
            self.states[index] = new_state

    def get_states(self, dump_optimizer=False):
        import pickle
        st = {k: _state_to_np(v) for k, v in self.states.items()}
        return pickle.dumps((st, self.optimizer.__class__.__name__)
                            if dump_optimizer else st)

    def set_states(self, states):
        import pickle
        st = pickle.loads(states)
        if isinstance(st, tuple):
            st = st[0]
        self.states = {k: _state_from_np(v) for k, v in st.items()}
        self.states_synced = {k: False for k in self.states}


def _state_to_np(s):
    if s is None:
        return None
    if isinstance(s, NDArray):
        return s.asnumpy()
    if isinstance(s, (tuple, list)):
        return tuple(_state_to_np(x) for x in s)
    return s


def _state_from_np(s):
    if s is None:
        return None
    if isinstance(s, onp.ndarray):
        return nd.array(s)
    if isinstance(s, tuple):
        return tuple(_state_from_np(x) for x in s)
    return s


def get_updater(optimizer):
    return Updater(optimizer)
