/* XS glue for the C predict ABI (ref perl-package/AI-MXNetCapi — SWIG in
 * the reference; plain XS here). Resolves libmxtpu_predict.so at boot via
 * dlopen (path from MXTPU_PREDICT_LIB or the loader path). */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include <dlfcn.h>
#include <stdint.h>

typedef const char* (*fn_err_t)(void);
typedef int (*fn_create_t)(const char*, void**);
typedef int (*fn_int_t)(void*, int*);
typedef int (*fn_shape_t)(void*, int, int64_t*, int, int*);
typedef int (*fn_dtype_t)(void*, int, char*, int);
typedef int (*fn_setin_t)(void*, int, const void*, int64_t);
typedef int (*fn_fwd_t)(void*);
typedef int (*fn_getout_t)(void*, int, void*, int64_t);
typedef int (*fn_free_t)(void*);

static fn_err_t    p_err;
static fn_create_t p_create;
static fn_int_t    p_nin, p_nout;
static fn_shape_t  p_inshape, p_outshape;
static fn_dtype_t  p_indtype, p_outdtype;
static fn_setin_t  p_setin;
static fn_fwd_t    p_fwd;
static fn_getout_t p_getout;
static fn_free_t   p_free;

static void ensure_lib(pTHX) {
    static void* so = NULL;
    if (so) return;
    const char* path = getenv("MXTPU_PREDICT_LIB");
    if (!path || !*path) path = "libmxtpu_predict.so";
    so = dlopen(path, RTLD_NOW | RTLD_GLOBAL);
    if (!so) croak("cannot dlopen %s: %s", path, dlerror());
    p_err      = (fn_err_t)   dlsym(so, "MXTPUPredGetLastError");
    p_create   = (fn_create_t)dlsym(so, "MXTPUPredCreate");
    p_nin      = (fn_int_t)   dlsym(so, "MXTPUPredNumInputs");
    p_nout     = (fn_int_t)   dlsym(so, "MXTPUPredNumOutputs");
    p_inshape  = (fn_shape_t) dlsym(so, "MXTPUPredGetInputShape");
    p_outshape = (fn_shape_t) dlsym(so, "MXTPUPredGetOutputShape");
    p_indtype  = (fn_dtype_t) dlsym(so, "MXTPUPredGetInputDType");
    p_outdtype = (fn_dtype_t) dlsym(so, "MXTPUPredGetOutputDType");
    p_setin    = (fn_setin_t) dlsym(so, "MXTPUPredSetInput");
    p_fwd      = (fn_fwd_t)   dlsym(so, "MXTPUPredForward");
    p_getout   = (fn_getout_t)dlsym(so, "MXTPUPredGetOutput");
    p_free     = (fn_free_t)  dlsym(so, "MXTPUPredFree");
    if (!p_create || !p_fwd) croak("libmxtpu_predict.so: missing symbols");
}

static void check(pTHX_ int rc) {
    if (rc != 0) croak("%s", p_err ? p_err() : "mxtpu predict error");
}

MODULE = AI::MXNetTPU  PACKAGE = AI::MXNetTPU

PROTOTYPES: DISABLE

IV
_create(path)
    const char* path
  CODE:
    ensure_lib(aTHX);
    void* h = NULL;
    check(aTHX_ p_create(path, &h));
    RETVAL = PTR2IV(h);
  OUTPUT:
    RETVAL

int
_num_inputs(h)
    IV h
  CODE:
    int n = 0;
    check(aTHX_ p_nin(INT2PTR(void*, h), &n));
    RETVAL = n;
  OUTPUT:
    RETVAL

int
_num_outputs(h)
    IV h
  CODE:
    int n = 0;
    check(aTHX_ p_nout(INT2PTR(void*, h), &n));
    RETVAL = n;
  OUTPUT:
    RETVAL

void
_output_shape(h, idx)
    IV h
    int idx
  PPCODE:
    int64_t shp[16];
    int nd = 0;
    check(aTHX_ p_outshape(INT2PTR(void*, h), idx, shp, 16, &nd));
    for (int i = 0; i < nd; ++i)
        XPUSHs(sv_2mortal(newSViv((IV)shp[i])));

void
_input_shape(h, idx)
    IV h
    int idx
  PPCODE:
    int64_t shp[16];
    int nd = 0;
    check(aTHX_ p_inshape(INT2PTR(void*, h), idx, shp, 16, &nd));
    for (int i = 0; i < nd; ++i)
        XPUSHs(sv_2mortal(newSViv((IV)shp[i])));

void
_set_input(h, idx, bytes)
    IV h
    int idx
    SV* bytes
  CODE:
    STRLEN len;
    const char* buf = SvPVbyte(bytes, len);
    check(aTHX_ p_setin(INT2PTR(void*, h), idx, buf, (int64_t)len));

void
_forward(h)
    IV h
  CODE:
    check(aTHX_ p_fwd(INT2PTR(void*, h)));

SV*
_get_output(h, idx, nbytes)
    IV h
    int idx
    IV nbytes
  CODE:
    SV* out = newSV((STRLEN)nbytes);
    SvPOK_on(out);
    check(aTHX_ p_getout(INT2PTR(void*, h), idx, SvPVX(out), (int64_t)nbytes));
    SvCUR_set(out, (STRLEN)nbytes);
    RETVAL = out;
  OUTPUT:
    RETVAL

void
_free(h)
    IV h
  CODE:
    p_free(INT2PTR(void*, h));
