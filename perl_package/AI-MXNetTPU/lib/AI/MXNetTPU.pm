package AI::MXNetTPU;
# Perl inference binding (ref perl-package/AI-MXNet — full framework there;
# here the deployment surface: run .mxtpu serving artifacts through the
# flat C predict ABI, the same contract cpp_package uses).
use strict;
use warnings;
require DynaLoader;
our @ISA = ('DynaLoader');
our $VERSION = '0.01';
bootstrap AI::MXNetTPU $VERSION;

package AI::MXNetTPU::Predictor;
use strict;
use warnings;

sub new {
    my ($class, $path) = @_;
    my $h = AI::MXNetTPU::_create($path);
    return bless { handle => $h }, $class;
}

sub num_inputs  { AI::MXNetTPU::_num_inputs($_[0]{handle}) }
sub num_outputs { AI::MXNetTPU::_num_outputs($_[0]{handle}) }
sub input_shape  { my @s = AI::MXNetTPU::_input_shape($_[0]{handle}, $_[1] // 0); \@s }
sub output_shape { my @s = AI::MXNetTPU::_output_shape($_[0]{handle}, $_[1] // 0); \@s }

# floats in/out as perl lists (pack f* — float32 row-major)
sub set_input {
    my ($self, $idx, @vals) = @_;
    AI::MXNetTPU::_set_input($self->{handle}, $idx, pack('f*', @vals));
}

sub forward { AI::MXNetTPU::_forward($_[0]{handle}) }

sub get_output {
    my ($self, $idx) = @_;
    my $shape = $self->output_shape($idx);
    my $n = 1; $n *= $_ for @$shape;
    my $bytes = AI::MXNetTPU::_get_output($self->{handle}, $idx, $n * 4);
    return [unpack('f*', $bytes)];
}

sub DESTROY { AI::MXNetTPU::_free($_[0]{handle}) if $_[0]{handle} }

1;
