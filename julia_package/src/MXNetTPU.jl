# Julia binding for incubator_mxnet_tpu (ref julia/ in upstream MXNet).
#
# Rides the flat C ABI in libmxtpu_predict.so via ccall — no build step, no
# binary dependency beyond the shared library the Python side compiles
# (incubator_mxnet_tpu.native.lib.build_predict()):
#   * Predictor: load an exported .mxtpu serving artifact and run inference
#     (MXTPUPred* — ref MXPredCreate family), and
#   * NDArray + invoke: name-dispatched EAGER operator calls
#     (MXTPUNDCreate/MXTPUImperativeInvoke — ref MXImperativeInvokeEx), so
#     any operator registered in the nd/nd.contrib table is callable from
#     Julia by name.
#
# Point MXTPU_PREDICT_LIB at the .so, or place this package next to the
# repo so the default relative path resolves. Julia arrays are column-major;
# the ABI is row-major — conversions below transpose so that the LOGICAL
# shapes match the Python frontend exactly.
module MXNetTPU

export NDArray, invoke_op, Predictor, set_input!, forward!, get_output,
       attach_grad!, recording, backward!, grad, set_data!

const _default_lib = normpath(joinpath(@__DIR__, "..", "..",
    "incubator_mxnet_tpu", "native", "libmxtpu_predict.so"))
const _lib = Ref{String}(_default_lib)

function __init__()
    _lib[] = get(ENV, "MXTPU_PREDICT_LIB", _default_lib)
end

_lasterr() = unsafe_string(ccall((:MXTPUPredGetLastError, _lib[]), Cstring, ()))
_check(rc::Integer) = rc == 0 || error("MXNetTPU: " * _lasterr())

# ------------------------------------------------------------------ dtypes
const _JL2NP = Dict{DataType,String}(
    Float32 => "float32", Float64 => "float64", Int32 => "int32",
    Int64 => "int64", Int8 => "int8", UInt8 => "uint8", Int16 => "int16",
    Bool => "bool")
const _NP2JL = Dict{String,DataType}(v => k for (k, v) in _JL2NP)

# ------------------------------------------------------------- tiny JSON
# (op attributes only: numbers, strings, booleans, tuples/vectors thereof)
_json(x::Real) = x isa Bool ? string(x) : string(x)
_json(x::AbstractString) = "\"" * x * "\""
_json(x::Union{Tuple,AbstractVector}) =
    "[" * join([_json(v) for v in x], ",") * "]"
_json(d::AbstractDict) =
    "{" * join(["\"" * string(k) * "\":" * _json(v) for (k, v) in d], ",") *
    "}"

# row-major (C) <-> column-major (Julia) conversion, shared by every
# upload path
_c_order(arr::AbstractArray) = ndims(arr) <= 1 ? arr :
    permutedims(arr, reverse(ntuple(identity, ndims(arr))))

# ------------------------------------------------------------- NDArray
mutable struct NDArray
    handle::Ptr{Cvoid}
    function NDArray(h::Ptr{Cvoid})
        x = new(h)
        finalizer(x) do y
            ccall((:MXTPUNDFree, _lib[]), Cint, (Ptr{Cvoid},), y.handle)
        end
        x
    end
end

"""NDArray(a::Array) — upload a Julia array. The logical shape seen by the
framework equals `size(a)` (the row-major transpose happens here)."""
function NDArray(a::AbstractArray{T}) where {T}
    haskey(_JL2NP, T) || error("unsupported element type $T")
    arr = Array(a)
    c_order = _c_order(arr)
    shape = Int64[size(arr)...]
    h = Ref{Ptr{Cvoid}}(C_NULL)
    _check(ccall((:MXTPUNDCreate, _lib[]), Cint,
                 (Cstring, Ptr{Int64}, Cint, Ptr{Cvoid}, Int64,
                  Ptr{Ptr{Cvoid}}),
                 _JL2NP[T], shape, ndims(arr), c_order,
                 Int64(sizeof(c_order)), h))
    NDArray(h[])
end

function Base.size(x::NDArray)
    nd = Ref{Cint}(0)
    _check(ccall((:MXTPUNDGetShape, _lib[]), Cint,
                 (Ptr{Cvoid}, Ptr{Int64}, Cint, Ptr{Cint}),
                 x.handle, C_NULL, 0, nd))
    shape = Vector{Int64}(undef, nd[])
    _check(ccall((:MXTPUNDGetShape, _lib[]), Cint,
                 (Ptr{Cvoid}, Ptr{Int64}, Cint, Ptr{Cint}),
                 x.handle, shape, nd[], nd))
    Tuple(shape)
end

function _dtype(x::NDArray)
    buf = Vector{UInt8}(undef, 32)
    _check(ccall((:MXTPUNDGetDType, _lib[]), Cint,
                 (Ptr{Cvoid}, Ptr{UInt8}, Cint), x.handle, buf, 32))
    _NP2JL[unsafe_string(pointer(buf))]
end

"""Array(x::NDArray) — download to a Julia array (logical shape/order
matching the Python frontend)."""
function Base.Array(x::NDArray)
    T = _dtype(x)
    shape = size(x)
    nb = Ref{Int64}(0)
    _check(ccall((:MXTPUNDGetData, _lib[]), Cint,
                 (Ptr{Cvoid}, Ptr{Cvoid}, Int64, Ptr{Int64}),
                 x.handle, C_NULL, 0, nb))
    raw = Vector{UInt8}(undef, nb[])
    _check(ccall((:MXTPUNDGetData, _lib[]), Cint,
                 (Ptr{Cvoid}, Ptr{Cvoid}, Int64, Ptr{Int64}),
                 x.handle, raw, nb[], C_NULL))
    vals = reinterpret(T, raw)
    isempty(shape) && return collect(vals)[1]
    length(shape) == 1 && return collect(vals)
    a = reshape(collect(vals), reverse(shape))         # C bytes, rev dims
    permutedims(a, reverse(ntuple(identity, length(shape))))
end

"""invoke_op(op, inputs...; kwargs...) — name-dispatched eager operator
call (≙ MXImperativeInvokeEx; named to avoid colliding with
`Base.invoke`). `invoke_op("dot", a, b)`, `invoke_op("sum", a; axis=1)`,
`invoke_op("linalg.gemm2", a, b)`. Returns a Vector{NDArray} (most ops
have one output)."""
function invoke_op(op::AbstractString, inputs::NDArray...; cap::Integer = 8,
                   kwargs...)
    ins = Ptr{Cvoid}[x.handle for x in inputs]
    outs = fill(C_NULL, cap)
    n = Ref{Cint}(0)
    kw = isempty(kwargs) ? "" :
        _json(Dict(string(k) => v for (k, v) in kwargs))
    _check(ccall((:MXTPUImperativeInvoke, _lib[]), Cint,
                 (Cstring, Ptr{Ptr{Cvoid}}, Cint, Cstring, Ptr{Ptr{Cvoid}},
                  Cint, Ptr{Cint}),
                 op, ins, length(ins), kw, outs, cap, n))
    [NDArray(Ptr{Cvoid}(outs[i])) for i in 1:n[]]
end

# ------------------------------------------------------------- autograd
# (≙ MXAutogradSetIsRecording / MXAutogradBackwardEx / MXNDArrayGetGrad —
# the slice that lets Julia TRAIN, not just run inference)

"""attach_grad!(x) — mark x as a differentiable leaf."""
attach_grad!(x::NDArray) =
    _check(ccall((:MXTPUNDAttachGrad, _lib[]), Cint, (Ptr{Cvoid},),
                 x.handle))

"""recording(f) — run f() inside an autograd tape scope:
`loss = recording(() -> invoke_op("sum", invoke_op("dot", x, w)[1])[1])`."""
function recording(f)
    _check(ccall((:MXTPUAutogradRecordBegin, _lib[]), Cint, ()))
    try
        return f()
    finally
        _check(ccall((:MXTPUAutogradRecordEnd, _lib[]), Cint, ()))
    end
end

"""backward!(loss) — reverse pass from a (scalar) recorded output."""
backward!(loss::NDArray) =
    _check(ccall((:MXTPUNDBackward, _lib[]), Cint, (Ptr{Cvoid},),
                 loss.handle))

"""grad(x) — the gradient accumulated on leaf x (a new NDArray)."""
function grad(x::NDArray)
    h = Ref{Ptr{Cvoid}}(C_NULL)
    _check(ccall((:MXTPUNDGetGrad, _lib[]), Cint,
                 (Ptr{Cvoid}, Ptr{Ptr{Cvoid}}), x.handle, h))
    NDArray(h[])
end

"""set_data!(x, a) — overwrite x's buffer from a Julia array (the
optimizer-update writeback for Julia-side training loops)."""
function set_data!(x::NDArray, a::AbstractArray{T}) where {T}
    arr = Array(a)
    c_order = _c_order(arr)
    _check(ccall((:MXTPUNDSetData, _lib[]), Cint,
                 (Ptr{Cvoid}, Cstring, Ptr{Cvoid}, Int64),
                 x.handle, _JL2NP[T], c_order, Int64(sizeof(c_order))))
end

# ------------------------------------------------------------- Predictor
mutable struct Predictor
    handle::Ptr{Cvoid}
    function Predictor(path::AbstractString)
        h = Ref{Ptr{Cvoid}}(C_NULL)
        _check(ccall((:MXTPUPredCreate, _lib[]), Cint,
                     (Cstring, Ptr{Ptr{Cvoid}}), path, h))
        p = new(h[])
        finalizer(p) do q
            ccall((:MXTPUPredFree, _lib[]), Cint, (Ptr{Cvoid},), q.handle)
        end
        p
    end
end

"""set_input!(p, index, a) — stage input `index` (0-based, matching the C
ABI) from a Julia array."""
function set_input!(p::Predictor, index::Integer, a::AbstractArray{T}) where {T}
    arr = Array(a)
    c_order = _c_order(arr)
    _check(ccall((:MXTPUPredSetInput, _lib[]), Cint,
                 (Ptr{Cvoid}, Cint, Ptr{Cvoid}, Int64),
                 p.handle, index, c_order, Int64(sizeof(c_order))))
end

forward!(p::Predictor) =
    _check(ccall((:MXTPUPredForward, _lib[]), Cint, (Ptr{Cvoid},), p.handle))

function _out_shape(p::Predictor, index::Integer)
    nd = Ref{Cint}(0)
    shape = Vector{Int64}(undef, 16)
    _check(ccall((:MXTPUPredGetOutputShape, _lib[]), Cint,
                 (Ptr{Cvoid}, Cint, Ptr{Int64}, Cint, Ptr{Cint}),
                 p.handle, index, shape, 16, nd))
    Tuple(shape[1:nd[]])
end

"""get_output(p, index) — fetch output `index` (0-based) as Float32 array."""
function get_output(p::Predictor, index::Integer)
    shape = _out_shape(p, index)
    n = prod(shape)
    buf = Vector{Float32}(undef, n)
    _check(ccall((:MXTPUPredGetOutput, _lib[]), Cint,
                 (Ptr{Cvoid}, Cint, Ptr{Cvoid}, Int64),
                 p.handle, index, buf, Int64(4n)))
    length(shape) <= 1 && return buf
    a = reshape(buf, reverse(shape))
    permutedims(a, reverse(ntuple(identity, length(shape))))
end

end # module
