/* C mirror of the exact ccall sequence julia_package/src/MXNetTPU.jl makes
 * against libmxtpu_predict.so — the CI stand-in for a Julia interpreter
 * (absent from this image). Every call below corresponds 1:1 to a ccall in
 * the module: same symbols, same argument types, same order.
 *
 * Usage: ccall_harness <libmxtpu_predict.so> [model.mxtpu input.bin]
 * Prints op results one float per line, section-tagged, parsed by
 * tests/test_julia_package.py.
 *
 * Build: gcc -O2 ccall_harness.c -ldl -o ccall_harness
 */
#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef int (*nd_create_t)(const char*, const int64_t*, int, const void*,
                           int64_t, void**);
typedef int (*nd_shape_t)(void*, int64_t*, int, int*);
typedef int (*nd_dtype_t)(void*, char*, int);
typedef int (*nd_data_t)(void*, void*, int64_t, int64_t*);
typedef int (*nd_free_t)(void*);
typedef int (*invoke_t)(const char*, void**, int, const char*, void**, int,
                        int*);
typedef const char* (*lasterr_t)(void);
typedef int (*pred_create_t)(const char*, void**);
typedef int (*pred_setin_t)(void*, int, const void*, int64_t);
typedef int (*pred_fwd_t)(void*);
typedef int (*pred_oshape_t)(void*, int, int64_t*, int, int*);
typedef int (*pred_out_t)(void*, int, void*, int64_t);
typedef int (*pred_free_t)(void*);

static lasterr_t g_err;

#define CHECK(rc)                                                     \
  do {                                                                \
    if ((rc) != 0) {                                                  \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,         \
              g_err ? g_err() : "?");                                 \
      return 1;                                                       \
    }                                                                 \
  } while (0)

static void print_nd(const char* tag, void* h, nd_shape_t nd_shape,
                     nd_data_t nd_data) {
  int64_t shape[16];
  int ndim = 0;
  nd_shape(h, shape, 16, &ndim);
  int64_t nb = 0;
  nd_data(h, NULL, 0, &nb);
  float* buf = (float*)malloc((size_t)nb);
  nd_data(h, buf, nb, NULL);
  printf("%s", tag);
  for (int i = 0; i < ndim; ++i) printf(" %lld", (long long)shape[i]);
  printf("\n");
  for (int64_t i = 0; i < nb / 4; ++i) printf("%.6e\n", buf[i]);
  free(buf);
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <libmxtpu_predict.so> [model input.bin]\n",
            argv[0]);
    return 2;
  }
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    fprintf(stderr, "dlopen: %s\n", dlerror());
    return 1;
  }
  nd_create_t nd_create = (nd_create_t)dlsym(lib, "MXTPUNDCreate");
  nd_shape_t nd_shape = (nd_shape_t)dlsym(lib, "MXTPUNDGetShape");
  nd_dtype_t nd_dtype = (nd_dtype_t)dlsym(lib, "MXTPUNDGetDType");
  nd_data_t nd_data = (nd_data_t)dlsym(lib, "MXTPUNDGetData");
  nd_free_t nd_free = (nd_free_t)dlsym(lib, "MXTPUNDFree");
  invoke_t invoke = (invoke_t)dlsym(lib, "MXTPUImperativeInvoke");
  g_err = (lasterr_t)dlsym(lib, "MXTPUNDGetLastError");
  if (!nd_create || !nd_shape || !nd_dtype || !nd_data || !nd_free ||
      !invoke || !g_err) {
    fprintf(stderr, "missing symbols\n");
    return 1;
  }

  /* --- NDArray(Float32[1 2 3; 4 5 6]) and ones(2,3): row-major bytes --- */
  float a_data[6] = {1, 2, 3, 4, 5, 6};
  float b_data[6] = {1, 1, 1, 1, 1, 1};
  int64_t shape23[2] = {2, 3};
  void *a = NULL, *b = NULL;
  CHECK(nd_create("float32", shape23, 2, a_data, sizeof(a_data), &a));
  CHECK(nd_create("float32", shape23, 2, b_data, sizeof(b_data), &b));

  char dt[32];
  CHECK(nd_dtype(a, dt, 32));
  printf("DTYPE %s\n", dt);

  /* --- invoke("broadcast_add", a, b) --- */
  void* outs[8];
  int n_out = 0;
  void* ins[2] = {a, b};
  CHECK(invoke("broadcast_add", ins, 2, "", outs, 8, &n_out));
  if (n_out != 1) return 1;
  print_nd("ADD", outs[0], nd_shape, nd_data);
  CHECK(nd_free(outs[0]));

  /* --- invoke("sum", a; axis=1): kwargs as the same JSON Julia emits --- */
  void* ins1[1] = {a};
  CHECK(invoke("sum", ins1, 1, "{\"axis\":1}", outs, 8, &n_out));
  print_nd("SUM", outs[0], nd_shape, nd_data);
  CHECK(nd_free(outs[0]));

  /* --- invoke("linalg.gemm2", a, aT): dotted sub-namespace dispatch --- */
  float at_data[6] = {1, 4, 2, 5, 3, 6};
  int64_t shape32[2] = {3, 2};
  void* at = NULL;
  CHECK(nd_create("float32", shape32, 2, at_data, sizeof(at_data), &at));
  void* ins2[2] = {a, at};
  CHECK(invoke("linalg.gemm2", ins2, 2, "", outs, 8, &n_out));
  print_nd("GEMM", outs[0], nd_shape, nd_data);
  CHECK(nd_free(outs[0]));

  /* --- error path: unknown op reports through the error string --- */
  if (invoke("not_a_real_op", ins1, 1, "", outs, 8, &n_out) == 0) {
    fprintf(stderr, "unknown op unexpectedly succeeded\n");
    return 1;
  }
  if (!strstr(g_err(), "not_a_real_op")) {
    fprintf(stderr, "error string missing op name: %s\n", g_err());
    return 1;
  }
  printf("ERRPATH ok\n");

  CHECK(nd_free(a));
  CHECK(nd_free(b));
  CHECK(nd_free(at));

  /* --- TRAIN: the autograd slice (attach_grad!/recording/backward!/grad)
   * One SGD step on w for loss = sum((x*w - y)^2); the gradient is checked
   * against the closed form 2*x^T*(x*w - y) computed right here in C. --- */
  {
    typedef int (*v_t)(void*);
    typedef int (*v0_t)(void);
    typedef int (*gg_t)(void*, void**);
    v_t nd_attach = (v_t)dlsym(lib, "MXTPUNDAttachGrad");
    v0_t rec_begin = (v0_t)dlsym(lib, "MXTPUAutogradRecordBegin");
    v0_t rec_end = (v0_t)dlsym(lib, "MXTPUAutogradRecordEnd");
    v_t nd_backward = (v_t)dlsym(lib, "MXTPUNDBackward");
    gg_t nd_grad = (gg_t)dlsym(lib, "MXTPUNDGetGrad");
    if (!nd_attach || !rec_begin || !rec_end || !nd_backward || !nd_grad) {
      fprintf(stderr, "missing autograd symbols\n");
      return 1;
    }
    float x_d[6] = {1, -1, 2, 0.5f, 3, -2};   /* (2,3) */
    float w_d[3] = {0.5f, -1, 2};             /* (3,)->(3,1) */
    float y_d[2] = {1, -1};                   /* (2,1) */
    int64_t s23[2] = {2, 3}, s31[2] = {3, 1}, s21[2] = {2, 1};
    void *xh = NULL, *wh = NULL, *yh = NULL;
    CHECK(nd_create("float32", s23, 2, x_d, sizeof(x_d), &xh));
    CHECK(nd_create("float32", s31, 2, w_d, sizeof(w_d), &wh));
    CHECK(nd_create("float32", s21, 2, y_d, sizeof(y_d), &yh));
    CHECK(nd_attach(wh));
    CHECK(rec_begin());
    void* t[2] = {xh, wh};
    CHECK(invoke("dot", t, 2, "", outs, 8, &n_out));
    void* pred = outs[0];
    void* t2[2] = {pred, yh};
    CHECK(invoke("broadcast_sub", t2, 2, "", outs, 8, &n_out));
    void* dif = outs[0];
    CHECK(invoke("square", &dif, 1, "", outs, 8, &n_out));
    void* sq = outs[0];
    CHECK(invoke("sum", &sq, 1, "", outs, 8, &n_out));
    void* loss = outs[0];
    CHECK(rec_end());
    CHECK(nd_backward(loss));
    void* gw = NULL;
    CHECK(nd_grad(wh, &gw));
    int64_t nb = 0;
    CHECK(nd_data(gw, NULL, 0, &nb));
    float gbuf[3];
    if (nb != sizeof(gbuf)) return 1;
    CHECK(nd_data(gw, gbuf, nb, NULL));
    /* closed form */
    float pred_d[2], want[3] = {0, 0, 0};
    for (int i = 0; i < 2; ++i) {
      pred_d[i] = 0;
      for (int j = 0; j < 3; ++j) pred_d[i] += x_d[i * 3 + j] * w_d[j];
    }
    for (int j = 0; j < 3; ++j)
      for (int i = 0; i < 2; ++i)
        want[j] += 2.0f * x_d[i * 3 + j] * (pred_d[i] - y_d[i]);
    for (int j = 0; j < 3; ++j) {
      float d = gbuf[j] - want[j];
      if (d < 0) d = -d;
      if (d > 1e-4f * (want[j] < 0 ? -want[j] : want[j]) + 1e-5f) {
        fprintf(stderr, "grad mismatch [%d]: %f vs %f\n", j, gbuf[j],
                want[j]);
        return 1;
      }
    }
    printf("TRAINOK\n");
    /* --- the SGD update itself: set_data!(w, w - 0.1*g) --- */
    typedef int (*sd_t)(void*, const char*, const void*, int64_t);
    sd_t nd_setdata = (sd_t)dlsym(lib, "MXTPUNDSetData");
    if (!nd_setdata) {
      fprintf(stderr, "missing MXTPUNDSetData\n");
      return 1;
    }
    float w_new[3];
    for (int j = 0; j < 3; ++j) w_new[j] = w_d[j] - 0.1f * gbuf[j];
    CHECK(nd_setdata(wh, "float32", w_new, sizeof(w_new)));
    float w_back[3];
    CHECK(nd_data(wh, w_back, sizeof(w_back), NULL));
    for (int j = 0; j < 3; ++j) {
      float d = w_back[j] - w_new[j];
      if (d < 0) d = -d;
      if (d > 1e-6f) {
        fprintf(stderr, "set_data round-trip mismatch [%d]\n", j);
        return 1;
      }
    }
    printf("SETDATAOK\n");
    CHECK(nd_free(pred)); CHECK(nd_free(dif)); CHECK(nd_free(sq));
    CHECK(nd_free(loss)); CHECK(nd_free(gw));
    CHECK(nd_free(xh)); CHECK(nd_free(wh)); CHECK(nd_free(yh));
  }

  /* --- Predictor path (same sequence as Predictor/set_input!/forward!) */
  if (argc >= 4) {
    pred_create_t pc = (pred_create_t)dlsym(lib, "MXTPUPredCreate");
    pred_setin_t psi = (pred_setin_t)dlsym(lib, "MXTPUPredSetInput");
    pred_fwd_t pf = (pred_fwd_t)dlsym(lib, "MXTPUPredForward");
    pred_oshape_t pos = (pred_oshape_t)dlsym(lib, "MXTPUPredGetOutputShape");
    pred_out_t po = (pred_out_t)dlsym(lib, "MXTPUPredGetOutput");
    pred_free_t pfr = (pred_free_t)dlsym(lib, "MXTPUPredFree");
    void* p = NULL;
    CHECK(pc(argv[2], &p));
    FILE* f = fopen(argv[3], "rb");
    if (!f) return 1;
    fseek(f, 0, SEEK_END);
    long n = ftell(f);
    fseek(f, 0, SEEK_SET);
    char* buf = (char*)malloc((size_t)n);
    if (fread(buf, 1, (size_t)n, f) != (size_t)n) return 1;
    fclose(f);
    CHECK(psi(p, 0, buf, n));
    free(buf);
    CHECK(pf(p));
    int64_t oshape[16];
    int ondim = 0;
    CHECK(pos(p, 0, oshape, 16, &ondim));
    int64_t total = 1;
    for (int i = 0; i < ondim; ++i) total *= oshape[i];
    float* obuf = (float*)malloc((size_t)(4 * total));
    CHECK(po(p, 0, obuf, 4 * total));
    printf("PRED");
    for (int i = 0; i < ondim; ++i) printf(" %lld", (long long)oshape[i]);
    printf("\n");
    for (int64_t i = 0; i < total; ++i) printf("%.6e\n", obuf[i]);
    free(obuf);
    CHECK(pfr(p));
  }
  printf("DONE\n");
  return 0;
}
