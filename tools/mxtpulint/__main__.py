"""CLI: ``python -m tools.mxtpulint [paths...] [options]``.

Exit codes: 0 = clean (all findings suppressed/baselined), 1 = new
findings, 2 = usage error. ``--json`` emits the shared report shape that
``tools/promcheck.py --json`` also produces, so CI aggregates both lint
gates with one parser.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (RULES, lint_paths, iter_py_files, load_baseline,
                   save_baseline, apply_baseline, make_report,
                   DEFAULT_BASELINE)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxtpulint",
        description="framework-aware static analysis for incubator_mxnet_tpu")
    ap.add_argument("paths", nargs="*", default=["incubator_mxnet_tpu"],
                    help="files/directories to lint "
                         "(default: incubator_mxnet_tpu)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the shared CI report shape on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/mxtpulint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma list of rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule_id, (title, _fn) in sorted(RULES.items()):
            print("%s  %s" % (rule_id, title))
        return 0

    only = None
    if args.rules:
        only = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = only - set(RULES)
        if unknown:
            print("unknown rule(s): %s" % ", ".join(sorted(unknown)),
                  file=sys.stderr)
            return 2

    paths = args.paths or ["incubator_mxnet_tpu"]
    # a typo'd/renamed path must fail loudly, not pass a vacuous gate
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print("path(s) do not exist: %s" % ", ".join(missing),
              file=sys.stderr)
        return 2
    files = list(iter_py_files(paths))
    if not files:
        print("no .py files found under: %s" % ", ".join(paths),
              file=sys.stderr)
        return 2
    findings = lint_paths(paths, only_rules=only)

    if args.write_baseline and only:
        # a rule-filtered rewrite would silently drop every OTHER rule's
        # grandfathered entries
        print("--write-baseline cannot be combined with --rules: it "
              "rewrites the whole baseline", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = save_baseline(args.baseline, findings)
        print("wrote %d finding(s) to %s" % (len(findings), path))
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, old = apply_baseline(findings, baseline)
    report = make_report("mxtpulint", new, baselined=len(old))

    if args.as_json:
        json.dump(report, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in new:
            print("%s:%d:%d: %s %s" % (f.path, f.line, f.col, f.rule,
                                       f.message))
        if new:
            by_rule = ", ".join("%s=%d" % kv
                                for kv in sorted(report["counts"].items()))
            print("mxtpulint: %d finding(s) [%s]%s"
                  % (len(new), by_rule,
                     " (+%d baselined)" % len(old) if old else ""))
            print("fix it, or suppress a reviewed exception with "
                  "'# mxtpulint: disable=<rule>' (docs/STATIC_ANALYSIS.md)")
        else:
            print("mxtpulint OK: 0 findings%s"
                  % (" (+%d baselined)" % len(old) if old else ""))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
