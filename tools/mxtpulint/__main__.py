"""CLI: ``python -m tools.mxtpulint [paths...] [options]``.

Exit codes:
  0  clean — every finding is fixed, inline-suppressed, or baselined
  1  new findings (printed human-readably, or as --json)
  2  usage error (unknown rule id, missing path, bad flag combination)

The run is two-phase: per-file rules over every path (tools/ and tests/
under the relaxed R003/R005/R006 profile), then the whole-program index +
interprocedural passes (R009-R011 and call-graph-aware R001) over the
full-profile files. ``--json`` emits the shared report shape that
``tools/promcheck.py --json`` also produces, so CI aggregates both lint
gates with one parser.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

from .core import (RULES, REPO_ROOT, RELAXED_RULES, audit_suppressions,
                   iter_py_files, load_baseline, save_baseline,
                   apply_baseline, make_report, rules_for_path,
                   DEFAULT_BASELINE)
from .interproc import PROJECT_RULES, analyze

_RELAXED = "/".join(sorted(RELAXED_RULES))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.mxtpulint",
        description="framework-aware static analysis for incubator_mxnet_tpu "
                    "(per-file rules + whole-program lock-order / "
                    "thread-safety / jit-retrace passes)",
        epilog="exit codes: 0 = clean (all findings fixed, suppressed, or "
               "baselined); 1 = new findings; 2 = usage error "
               "(unknown rule, missing path, bad flag combination, or a "
               "--rules selection every given path's profile masks)")
    ap.add_argument("paths", nargs="*", default=["incubator_mxnet_tpu"],
                    help="files/directories to lint "
                         "(default: incubator_mxnet_tpu; tools/ and tests/ "
                         "run the relaxed %s profile)" % _RELAXED)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the shared CI report shape on stdout")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/mxtpulint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--update-baseline", "--write-baseline",
                    action="store_true", dest="update_baseline",
                    help="rewrite the baseline file from the current "
                         "findings and exit 0 (no hand-editing; the goal "
                         "state is an empty baseline)")
    ap.add_argument("--rules", default=None,
                    help="comma list of rule ids to run (default: all)")
    ap.add_argument("--check-suppressions", action="store_true",
                    dest="check_suppressions",
                    help="also audit suppression hygiene: X001 flags "
                         "'# mxtpulint: disable=' comments whose rule no "
                         "longer fires at that line, X002 flags stale "
                         "baseline entries whose finding no longer "
                         "occurs (neither is baselineable; default-on "
                         "in the CI lint stage)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog (per-file + "
                         "whole-program) and exit")
    ap.add_argument("--timing", action="store_true",
                    help="print the lint wall time to stderr (the CI "
                         "stage budget-checks it)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule_id, (title, _fn) in sorted(RULES.items()):
            print("%s  %s" % (rule_id, title))
        for rule_id, (title, _fn) in sorted(PROJECT_RULES.items()):
            print("%s  %s  [whole-program]" % (rule_id, title))
        return 0

    only = None
    if args.rules:
        only = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = only - set(RULES) - set(PROJECT_RULES)
        if unknown:
            print("unknown rule(s): %s" % ", ".join(sorted(unknown)),
                  file=sys.stderr)
            return 2

    paths = args.paths or ["incubator_mxnet_tpu"]
    # a typo'd/renamed path must fail loudly, not pass a vacuous gate
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print("path(s) do not exist: %s" % ", ".join(missing),
              file=sys.stderr)
        return 2
    files = list(iter_py_files(paths))
    if not files:
        print("no .py files found under: %s" % ", ".join(paths),
              file=sys.stderr)
        return 2

    if args.update_baseline and only:
        # a rule-filtered rewrite would silently drop every OTHER rule's
        # grandfathered entries
        print("--update-baseline cannot be combined with --rules: it "
              "rewrites the whole baseline", file=sys.stderr)
        return 2
    if args.check_suppressions and only:
        # a rule-filtered raw run cannot tell a FIXED suppression from a
        # merely unselected one — the audit would flag live suppressions
        # of every rule outside the selection
        print("--check-suppressions cannot be combined with --rules: the "
              "audit needs the full rule set to know what still fires",
              file=sys.stderr)
        return 2
    if args.check_suppressions and args.update_baseline:
        print("--check-suppressions cannot be combined with "
              "--update-baseline: X001/X002 audit findings are not "
              "baselineable", file=sys.stderr)
        return 2

    if only:
        # explicit rule selection where EVERY file's path profile masks
        # every requested rule would lint nothing — that's the same
        # vacuous green the missing-path check exists to prevent
        # (relaxed tools/tests files also never run whole-program rules)
        def runnable(path):
            profile = rules_for_path(os.path.relpath(path, REPO_ROOT))
            return profile is None or bool(profile & only)
        if not any(runnable(f) for f in files):
            print("requested rule(s) %s do not apply to any given path: "
                  "tools/ and tests/ run the relaxed profile (%s) only"
                  % (", ".join(sorted(only)), _RELAXED), file=sys.stderr)
            return 2

    t0 = time.perf_counter()
    findings = analyze(files, only_rules=only)
    elapsed = time.perf_counter() - t0
    if args.timing:
        print("mxtpulint: %d file(s) in %.2fs" % (len(files), elapsed),
              file=sys.stderr)

    if args.update_baseline:
        path = save_baseline(args.baseline, findings)
        print("wrote %d finding(s) to %s" % (len(findings), path))
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, old = apply_baseline(findings, baseline)
    if args.check_suppressions:
        # the audit's raw view re-runs unfiltered (suppressed findings
        # kept) so each disable comment is judged against what actually
        # fires; X001/X002 land in ``new`` directly — never baselined
        raw = analyze(files, keep_suppressed=True)
        new.extend(audit_suppressions(files, raw, live_findings=findings,
                                      baseline_counts=baseline))
        new.sort(key=lambda f: (f.path, f.line, f.rule))
    report = make_report("mxtpulint", new, baselined=len(old))

    if args.as_json:
        json.dump(report, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        for f in new:
            print("%s:%d:%d: %s %s" % (f.path, f.line, f.col, f.rule,
                                       f.message))
        if new:
            by_rule = ", ".join("%s=%d" % kv
                                for kv in sorted(report["counts"].items()))
            print("mxtpulint: %d finding(s) [%s]%s"
                  % (len(new), by_rule,
                     " (+%d baselined)" % len(old) if old else ""))
            print("fix it, or suppress a reviewed exception with "
                  "'# mxtpulint: disable=<rule>' (docs/STATIC_ANALYSIS.md)")
        else:
            print("mxtpulint OK: 0 findings%s"
                  % (" (+%d baselined)" % len(old) if old else ""))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
