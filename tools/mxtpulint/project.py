"""Phase 1 of the whole-program analyzer: the project index.

Per-file rules (rules.py) see one AST at a time; the properties that
actually kill an XLA-era framework in production — lock-order inversions
between threads started in different modules, unlocked state shared with
a worker loop, Python values that silently retrigger a trace — are
*whole-program* facts. This module builds the index the interprocedural
passes (interproc.py) run over:

- **module symbol tables** with import/alias resolution (``import x as
  y``, ``from .m import f as g``, relative levels) across the package,
- **a call graph**: calls resolved through imports, module symbols,
  nested defs, ``self`` method resolution (including base classes and
  ``self.attr`` instances whose class is known from ``__init__``), and
  locally-typed variables (``entry = _ModelEntry(...)``),
- **per-function summaries**: locks acquired (with the set of locks
  already *held* at each acquisition — the deadlock edge), threads/timers
  spawned and their resolved targets, attributes read/written on
  ``self``/classes/module globals (with the locks held at each access),
  host-device sync sites, and jit-boundary facts (functions handed to
  ``jax.jit``-family wrappers, names bound to jitted callables or
  ``TrainStep``/``EvalStep`` instances, and their call sites).

Everything is still stdlib ``ast`` — no imports of the analyzed code, so
the index phase can run on a box with no jax at all. Precision limits are
deliberate and documented in docs/STATIC_ANALYSIS.md: no closures-as-data
tracking, no return-type inference, mutations via method calls
(``d.pop``, ``l.append``) are not writes. The passes are tuned so those
limits cost recall, never precision.
"""
from __future__ import annotations

import ast

from .core import get_context, iter_py_files, rules_for_path, terminal_name

__all__ = ["ProjectIndex", "ModuleInfo", "ClassInfo", "FunctionInfo",
           "build_index"]

#: jax transforms whose function argument gets TRACED (calling the result
#: re-traces on new static/shape keys) — the jit-boundary markers.
JIT_WRAPPERS = {"jit", "checkpoint", "value_and_grad", "grad", "vmap",
                "pmap"}

#: constructors whose instances are compiled-step callables: calling one
#: goes through a shape/dtype-keyed executable cache.
STEP_CLASSES = {"TrainStep", "EvalStep"}

#: AOT executable-cache entry point (incubator_mxnet_tpu/aot.py): a call
#: site hands a builder to the shared compiled-executable cache, keyed by
#: the CacheKey argument — the same retrace-hazard surface as a direct
#: jax.jit call (an unhashable/varying argument here defeats the cache or
#: forces a rebuild per call), so R011 treats it as a jit boundary.
#: Covers the module-level facade only: the AOTCache.get_or_build METHOD
#: is reached through the CACHE instance global, which the indexer cannot
#: type (no instance typing for module-level objects) — callers are
#: expected to go through compile_cached.
AOT_BOUNDARY_FUNCS = {"compile_cached"}
AOT_MODULE_NAME = "aot"

_LOCK_CTORS = {"Lock", "RLock", "Semaphore", "BoundedSemaphore",
               "Condition"}
_EVENT_CTORS = {"Event"}


class ModuleInfo:
    __slots__ = ("relpath", "modkey", "dotted", "ctx", "functions",
                 "classes", "imports", "global_kinds", "globals_",
                 "global_lock_aliases", "global_reentrant",
                 "boundary_globals", "jit_marks_global")

    def __init__(self, relpath, modkey, dotted, ctx):
        self.relpath = relpath
        self.modkey = modkey            # relpath minus .py (rule key form)
        self.dotted = dotted            # import name
        self.ctx = ctx
        self.functions = {}             # top-level name -> FunctionInfo
        self.classes = {}               # name -> ClassInfo
        self.imports = {}               # local name -> ("module", dotted)
        #                                 | ("symbol", mod_dotted, symbol)
        self.global_kinds = {}          # module-level name -> kind string
        self.globals_ = set()           # every module-level assigned name
        self.global_lock_aliases = {}   # Condition(_lock) -> root name
        self.global_reentrant = set()   # RLock()/argless Condition() names
        self.boundary_globals = {}      # module-level jitted/step names
        self.jit_marks_global = set()   # fn keys jitted at module scope


class ClassInfo:
    __slots__ = ("name", "key", "module", "node", "base_names", "bases",
                 "methods", "attr_types", "lock_attrs", "reentrant_attrs",
                 "sync_attrs", "step_attrs")

    def __init__(self, name, key, module, node):
        self.name = name
        self.key = key                  # "modkey:Class"
        self.module = module
        self.node = node
        self.base_names = []            # raw base expressions (dump later)
        self.bases = []                 # resolved ClassInfo list
        self.methods = {}               # name -> FunctionInfo
        self.attr_types = {}            # self.X = ClassName() -> ClassInfo
        self.lock_attrs = {}            # attr -> canonical root attr
        self.reentrant_attrs = set()    # RLock()/argless Condition() attrs
        self.sync_attrs = set()         # Events/locals/queues: not state
        self.step_attrs = set()         # self.X = TrainStep()/EvalStep()

    def resolve_method(self, name, _seen=None):
        """Method resolution on ``self``: own methods, then base classes
        (depth-first over project-resolved bases)."""
        if name in self.methods:
            return self.methods[name]
        _seen = _seen or set()
        _seen.add(self.key)
        for base in self.bases:
            if base.key in _seen:
                continue
            m = base.resolve_method(name, _seen)
            if m is not None:
                return m
        return None

    def resolve_attr_type(self, attr):
        if attr in self.attr_types:
            return self.attr_types[attr]
        for base in self.bases:
            t = base.resolve_attr_type(attr)
            if t is not None:
                return t
        return None

    def lock_root(self, attr):
        """Canonical attr for a lock attr (Condition(self._lock) aliases
        back onto _lock); None when ``attr`` is not a lock."""
        seen = set()
        while attr in self.lock_attrs and attr not in seen:
            seen.add(attr)
            root = self.lock_attrs[attr]
            if root == attr:
                return attr
            attr = root
        return attr if attr in self.lock_attrs or attr in seen else None


class FunctionInfo:
    __slots__ = ("key", "qualname", "node", "module", "cls", "params",
                 "is_init", "calls", "acquires", "syncs", "state_writes",
                 "state_reads", "thread_targets", "jit_param_names",
                 "jit_marks", "jit_callsites", "nested", "parent",
                 "imports", "locals_", "global_decls")

    def __init__(self, key, qualname, node, module, cls):
        self.key = key                  # "modkey:Qual.name"
        self.qualname = qualname
        self.node = node
        self.module = module
        self.cls = cls                  # ClassInfo or None
        args = node.args
        self.params = [a.arg for a in
                       getattr(args, "posonlyargs", []) + args.args]
        self.is_init = cls is not None and node.name == "__init__"
        self.calls = []                 # (callee_key|None, node, held)
        self.acquires = []              # (held_tuple, lock_id, node)
        self.syncs = []                 # (what, node)
        self.state_writes = []          # (state_key, node, held)
        self.state_reads = []           # (state_key, node, held)
        self.thread_targets = []        # resolved fn keys
        self.jit_param_names = set()    # params this fn passes to jax.jit
        self.jit_marks = set()          # fn keys this fn passes to jax.jit
        self.jit_callsites = []         # (call_node, kind)
        self.nested = {}                # name -> fn key (direct children)
        self.parent = None              # enclosing function's key, if any
        self.imports = {}               # function-scoped deferred imports
        self.locals_ = set()
        self.global_decls = set()

    @property
    def params_no_self(self):
        if self.cls is not None and self.params \
                and self.params[0] in ("self", "cls"):
            return self.params[1:]
        return self.params


def _module_dotted(relpath):
    rel = relpath.replace("\\", "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    parts = rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _ctor_kind(value):
    """Classify a module/attr-level RHS: lock/event/tlocal/call/const."""
    if isinstance(value, ast.Call):
        name = terminal_name(value.func)
        if name in _LOCK_CTORS:
            return "lock"
        if name in _EVENT_CTORS:
            return "event"
        if name == "local" or (isinstance(value.func, ast.Attribute)
                               and value.func.attr == "local"):
            return "tlocal"
        return "call"
    if isinstance(value, ast.Constant):
        return "const"
    return "other"


class ProjectIndex:
    """The whole-program index: modules + classes + functions + the
    resolved call graph, ready for the interprocedural passes."""

    def __init__(self, root):
        self.root = root
        self.modules = {}               # relpath -> ModuleInfo
        self.by_dotted = {}             # dotted -> ModuleInfo
        self.functions = {}             # fn key -> FunctionInfo
        self.classes = {}               # class key -> ClassInfo
        self._reach_cache = None
        self._translock_cache = {}
        self._callers_cache = None

    # ------------------------------------------------------------ building
    def add_module(self, ctx):
        relpath = ctx.relpath
        mod = ModuleInfo(relpath, ctx.modkey, _module_dotted(relpath), ctx)
        self.modules[relpath] = mod
        self.by_dotted[mod.dotted] = mod
        self._scan_symbols(mod)
        return mod

    def _scan_symbols(self, mod):
        tree = mod.ctx.tree
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._scan_import(mod, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pass                    # functions enumerated below
            elif isinstance(node, ast.ClassDef):
                key = "%s:%s" % (mod.modkey, node.name)
                cls = ClassInfo(node.name, key, mod, node)
                cls.base_names = list(node.bases)
                mod.classes[node.name] = cls
                self.classes[key] = cls
                # class-BODY sync objects: `class C: _lock = Lock()` is
                # as real a lock as one assigned in __init__
                for stmt in node.body:
                    if not (isinstance(stmt, ast.Assign)
                            and isinstance(stmt.value, ast.Call)):
                        continue
                    kind = _ctor_kind(stmt.value)
                    ctor = terminal_name(stmt.value.func)
                    for t in stmt.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if kind == "lock":
                            cls.lock_attrs[t.id] = t.id
                            if ctor == "RLock" \
                                    or (ctor == "Condition"
                                        and not stmt.value.args) \
                                    or ctor in ("Semaphore",
                                                "BoundedSemaphore"):
                                cls.reentrant_attrs.add(t.id)
                        elif kind in ("event", "tlocal"):
                            cls.sync_attrs.add(t.id)
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                value = getattr(node, "value", None)
                kind = _ctor_kind(value) if value is not None else "other"
                for t in targets:
                    if isinstance(t, ast.Name):
                        mod.globals_.add(t.id)
                        mod.global_kinds[t.id] = kind
                        if kind == "lock" and isinstance(value, ast.Call):
                            ctor = terminal_name(value.func)
                            if ctor == "Condition" and value.args \
                                    and isinstance(value.args[0], ast.Name):
                                mod.global_lock_aliases[t.id] = \
                                    value.args[0].id
                            elif ctor == "RLock" \
                                    or (ctor == "Condition"
                                        and not value.args) \
                                    or ctor in ("Semaphore",
                                                "BoundedSemaphore"):
                                # reentrant (an argless Condition wraps a
                                # fresh RLock) — or a semaphore, whose
                                # capacity legally admits re-acquire
                                mod.global_reentrant.add(t.id)
                    elif isinstance(t, (ast.Tuple, ast.List)):
                        for elt in t.elts:
                            if isinstance(elt, ast.Name):
                                mod.globals_.add(elt.id)
                                mod.global_kinds[elt.id] = "other"
        # every function def in the file becomes a FunctionInfo
        for fnode, qual in mod.ctx.qualnames.items():
            cls = None
            for anc in mod.ctx.ancestors(fnode):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                if isinstance(anc, ast.ClassDef):
                    cls = mod.classes.get(anc.name)
                    break
            key = "%s:%s" % (mod.modkey, qual)
            info = FunctionInfo(key, qual, fnode, mod, cls)
            self.functions[key] = info
            if "." not in qual:
                mod.functions[fnode.name] = info
            if cls is not None and qual == "%s.%s" % (cls.name, fnode.name):
                cls.methods[fnode.name] = info
        # direct nested defs (for name resolution inside the parent)
        for key, info in list(self.functions.items()):
            if not key.startswith(mod.modkey + ":"):
                continue
            for child in ast.walk(info.node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and child is not info.node:
                    cqual = mod.ctx.qualnames.get(child)
                    if cqual == info.qualname + "." + child.name:
                        ckey = "%s:%s" % (mod.modkey, cqual)
                        info.nested[child.name] = ckey
                        if ckey in self.functions:
                            self.functions[ckey].parent = info.key
            # function-level (deferred) imports — the codebase's standard
            # import-cycle-avoidance idiom (`from .. import config`
            # inside a function) — bind FUNCTION-scoped aliases: merging
            # them module-wide would let two functions importing
            # different symbols under one local name mis-resolve each
            # other's calls (fabricated edges = false R009/R010/R011)
            stack = list(info.node.body)
            while stack:
                child = stack.pop()
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue            # nested fns collect their own
                if isinstance(child, (ast.Import, ast.ImportFrom)):
                    info.imports.update(self._import_bindings(mod, child))
                stack.extend(ast.iter_child_nodes(child))

    def _scan_import(self, mod, node):
        mod.imports.update(self._import_bindings(mod, node))

    def _import_bindings(self, mod, node):
        """{local name -> import entry} for one Import/ImportFrom node,
        with relative levels resolved against the file's package."""
        out = {}
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                out[local] = ("module", target)
            return out
        pkg = mod.dotted.split(".")
        if not mod.relpath.endswith("__init__.py"):
            pkg = pkg[:-1]
        if node.level:
            base = pkg[:len(pkg) - (node.level - 1)] if node.level > 1 \
                else pkg
            prefix = ".".join(base)
            target_mod = prefix + ("." + node.module if node.module else "")
        else:
            target_mod = node.module or ""
        for alias in node.names:
            local = alias.asname or alias.name
            sub = (target_mod + "." + alias.name) if target_mod \
                else alias.name
            # `from pkg import sub` where sub is a module of the project
            # binds the module; otherwise it binds a symbol
            out[local] = ("maybe_module", target_mod, alias.name, sub)
        return out

    def _finalize_table(self, table):
        for local, entry in list(table.items()):
            if entry[0] != "maybe_module":
                continue
            _kind, target_mod, name, sub = entry
            if sub in self.by_dotted:
                table[local] = ("module", sub)
            else:
                table[local] = ("symbol", target_mod, name)

    def finalize_imports(self):
        """Second pass once every module is registered: decide whether a
        ``from pkg import name`` bound a submodule or a symbol (for the
        module tables AND every function-scoped table), and resolve
        class bases."""
        for mod in self.modules.values():
            self._finalize_table(mod.imports)
        for fn in self.functions.values():
            self._finalize_table(fn.imports)
        for cls in self.classes.values():
            for base in cls.base_names:
                resolved = self._resolve_class_expr(cls.module, base)
                if resolved is not None:
                    cls.bases.append(resolved)

    def _lookup_fn_import(self, fn, name):
        """Function-scoped import binding for ``name``, walking the
        enclosing-function chain (a nested def sees its parents'
        deferred imports). Module-level imports are NOT consulted here —
        they sit later in the resolution order, after local shadowing."""
        cur = fn
        while cur is not None:
            if name in cur.imports:
                return cur.imports[name]
            cur = self.functions.get(cur.parent) if cur.parent else None
        return None

    def _resolve_class_expr(self, mod, expr):
        if isinstance(expr, ast.Name):
            if expr.id in mod.classes:
                return mod.classes[expr.id]
            imp = mod.imports.get(expr.id)
            if imp and imp[0] == "symbol":
                m = self.by_dotted.get(imp[1])
                if m is not None:
                    return m.classes.get(imp[2])
        elif isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            imp = mod.imports.get(expr.value.id)
            if imp and imp[0] == "module":
                m = self.by_dotted.get(imp[1])
                if m is not None:
                    return m.classes.get(expr.attr)
        return None

    def _jit_decorator(self, mod, fn_info):
        """Is this function decorated into a jit boundary? Handles
        ``@jax.jit``, ``@jit`` (imported from jax), and the
        ``@partial(jax.jit, ...)`` / ``@jax.jit(...)`` call forms —
        the most common jit spelling of all."""
        for dec in fn_info.node.decorator_list:
            target = dec
            if isinstance(dec, ast.Call):
                ext = self.resolve_external(mod, dec.func)
                if ext.endswith(".partial") and dec.args:
                    target = dec.args[0]    # partial(jax.jit, ...)
                else:
                    target = dec.func       # jax.jit(static_argnums=...)
            ext = self.resolve_external(mod, target)
            if ext.startswith("jax.") and ext.split(".")[-1] in JIT_WRAPPERS:
                return True
        return False

    def scan_module_boundaries(self):
        """Module-scope jit boundaries (after imports finalize):
        ``_jitted = jax.jit(model)`` / ``_step = EvalStep(net)`` at
        module level, and ``@jax.jit``-decorated functions, make calls
        through that NAME boundary call sites and the wrapped function
        traced — the common serving idioms."""
        for fn in self.functions.values():
            if self._jit_decorator(fn.module, fn):
                fn.module.jit_marks_global.add(fn.key)
                if "." not in fn.qualname:      # module-level name
                    fn.module.boundary_globals[fn.node.name] = "jit"
        for mod in self.modules.values():
            for node in mod.ctx.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    continue
                name = node.targets[0].id
                value = node.value
                ext = self.resolve_external(mod, value.func)
                if ext.startswith("jax.") \
                        and ext.split(".")[-1] in JIT_WRAPPERS:
                    mod.boundary_globals[name] = "jit"
                    if value.args and isinstance(value.args[0], ast.Name):
                        target = self.resolve_call_target(
                            mod, None, value.args[0], {})
                        if isinstance(target, FunctionInfo):
                            mod.jit_marks_global.add(target.key)
                    continue
                target = self.resolve_call_target(mod, None, value.func,
                                                  {})
                if isinstance(target, ClassInfo) and (
                        target.name in STEP_CLASSES
                        or any(b.name in STEP_CLASSES
                               for b in target.bases)):
                    mod.boundary_globals[name] = "step"

    def scan_class_attrs(self):
        """self.X = <ctor> scans across every method: attribute types,
        lock/event attrs (with Condition aliasing), step-callable attrs."""
        for cls in self.classes.values():
            for info in cls.methods.values():
                for node in ast.walk(info.node):
                    if not (isinstance(node, ast.Assign)
                            and len(node.targets) == 1):
                        continue
                    t = node.targets[0]
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    value = node.value
                    # `self.x = a if cond else b`: classify both arms
                    values = [value.body, value.orelse] \
                        if isinstance(value, ast.IfExp) else [value]
                    for v in values:
                        self._classify_self_attr(cls, info.module, t.attr, v)

    def _classify_self_attr(self, cls, mod, attr, value):
        kind = _ctor_kind(value)
        if kind == "lock":
            root = attr
            ctor = terminal_name(value.func) \
                if isinstance(value, ast.Call) else ""
            if ctor == "Condition" and value.args \
                    and isinstance(value.args[0], ast.Attribute) \
                    and isinstance(value.args[0].value, ast.Name) \
                    and value.args[0].value.id == "self":
                root = value.args[0].attr
            elif ctor == "RLock" \
                    or (ctor == "Condition" and not value.args) \
                    or ctor in ("Semaphore", "BoundedSemaphore"):
                cls.reentrant_attrs.add(attr)
            cls.lock_attrs[attr] = root
        elif kind in ("event", "tlocal"):
            cls.sync_attrs.add(attr)
        elif isinstance(value, ast.Call):
            target = self.resolve_call_target(mod, None, value.func, {})
            if isinstance(target, ClassInfo):
                cls.attr_types[attr] = target
                if target.name in STEP_CLASSES or any(
                        b.name in STEP_CLASSES for b in target.bases):
                    cls.step_attrs.add(attr)
            name = terminal_name(value.func)
            if name in ("Queue", "LifoQueue", "PriorityQueue", "deque"):
                cls.sync_attrs.add(attr)

    # --------------------------------------------------------- resolution
    def _resolve_import_entry(self, imp):
        """Import entry -> FunctionInfo/ClassInfo for a symbol binding
        (a bare module binding is not callable -> None)."""
        if imp and imp[0] == "symbol":
            m = self.by_dotted.get(imp[1])
            if m is not None:
                return m.functions.get(imp[2]) or m.classes.get(imp[2])
        return None

    def resolve_call_target(self, mod, fn, func, local_types):
        """Resolve a call's func expression to a FunctionInfo, ClassInfo,
        or None. ``fn`` may be None (class-attr pre-scan)."""
        if isinstance(func, ast.Name):
            name = func.id
            if fn is not None and name in fn.nested:
                return self.functions.get(fn.nested[name])
            if name in local_types:
                t = local_types[name]
                if isinstance(t, ClassInfo):
                    return t.resolve_method("__call__")
            if fn is not None:
                # function-scoped deferred imports bind tighter than any
                # module symbol (and than other functions' imports)
                imp = self._lookup_fn_import(fn, name)
                if imp is not None:
                    return self._resolve_import_entry(imp)
            # a parameter or plain local SHADOWS any sibling/module
            # symbol of the same name — resolving `def run(flush):
            # flush()` to a module-level flush() fabricates edges that
            # poison R009/R010/R011 ("a resolved edge is real" contract)
            if fn is not None and name in fn.locals_ \
                    and name not in fn.global_decls:
                return None
            if fn is not None:
                # siblings through the enclosing chain (inner1 calling
                # inner2, both defined in the same outer — the
                # worker-closure idiom)
                cur = self.functions.get(fn.parent) if fn.parent else None
                while cur is not None:
                    if name in cur.nested:
                        return self.functions.get(cur.nested[name])
                    cur = self.functions.get(cur.parent) \
                        if cur.parent else None
            if name in mod.functions:
                return mod.functions[name]
            if name in mod.classes:
                return mod.classes[name]
            return self._resolve_import_entry(mod.imports.get(name))
        if isinstance(func, ast.Attribute):
            base = func.value
            attr = func.attr
            if isinstance(base, ast.Name):
                if base.id == "self" and fn is not None \
                        and fn.cls is not None:
                    m = fn.cls.resolve_method(attr)
                    if m is not None:
                        return m
                    t = fn.cls.resolve_attr_type(attr)
                    if t is not None:   # self.step(...) on a typed attr
                        return t.resolve_method("__call__")
                    return None
                imp = None
                if fn is not None:
                    imp = self._lookup_fn_import(fn, base.id)
                if imp is None:
                    imp = mod.imports.get(base.id)
                if imp and imp[0] == "module":
                    m = self.by_dotted.get(imp[1])
                    if m is not None:
                        return m.functions.get(attr) or m.classes.get(attr)
                t = local_types.get(base.id)
                if isinstance(t, ClassInfo):
                    return t.resolve_method(attr)
                if base.id in mod.classes:
                    return mod.classes[base.id].resolve_method(attr)
                return None
            # self.attr.method(...) via a typed instance attribute
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" \
                    and fn is not None and fn.cls is not None:
                t = fn.cls.resolve_attr_type(base.attr)
                if t is not None:
                    return t.resolve_method(attr)
        return None

    def resolve_external(self, mod, func, fn=None):
        """Dotted EXTERNAL name of a call target through import aliases
        ('time.time', 'jax.jit', ...), or '' when unknown/project-local.
        Function-scoped deferred imports bind tighter than module ones."""
        if isinstance(func, ast.Name):
            imp = (self._lookup_fn_import(fn, func.id)
                   if fn is not None else None) \
                or mod.imports.get(func.id)
            if imp and imp[0] == "symbol" and imp[1] not in self.by_dotted:
                return "%s.%s" % (imp[1], imp[2])
            return ""
        if isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name):
            imp = (self._lookup_fn_import(fn, func.value.id)
                   if fn is not None else None) \
                or mod.imports.get(func.value.id)
            if imp and imp[0] == "module" and imp[1] not in self.by_dotted:
                return "%s.%s" % (imp[1], func.attr)
        return ""

    def canonical_lock(self, mod, fn, expr, local_types):
        """Canonical shared-lock id for an expression, or None.
        Module-level locks -> 'modkey::name'; instance locks ->
        'modkey::Class.attr' (type-level: one id per class attr)."""
        if isinstance(expr, ast.Name):
            name = expr.id
            if fn is not None and name in fn.locals_ \
                    and name not in fn.global_decls:
                return None             # function-local lock: not shared
            seen = set()
            while name in mod.global_lock_aliases and name not in seen:
                seen.add(name)
                name = mod.global_lock_aliases[name]
            if mod.global_kinds.get(name) == "lock":
                return "%s::%s" % (mod.modkey, name)
            return None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base == "self" and fn is not None and fn.cls is not None:
                cls, cur = fn.cls, fn.cls
                root = cur.lock_root(attr)
                if root is None:
                    for b in cur.bases:
                        root = b.lock_root(attr)
                        if root is not None:
                            cls = b
                            break
                if root is not None:
                    return "%s:%s.%s" % (cls.module.modkey, cls.name, root)
                return None
            imp = (self._lookup_fn_import(fn, base)
                   if fn is not None else None) or mod.imports.get(base)
            if imp and imp[0] == "module":
                m = self.by_dotted.get(imp[1])
                if m is not None and m.global_kinds.get(attr) == "lock":
                    name, seen = attr, set()
                    while name in m.global_lock_aliases and name not in seen:
                        seen.add(name)
                        name = m.global_lock_aliases[name]
                    return "%s::%s" % (m.modkey, name)
            t = local_types.get(base)
            if isinstance(t, ClassInfo):
                root = t.lock_root(attr)
                if root is not None:
                    return "%s:%s.%s" % (t.module.modkey, t.name, root)
            # ClassName._lock: a class-level lock taken through the class
            cls = mod.classes.get(base)
            if cls is None and imp and imp[0] == "symbol":
                m = self.by_dotted.get(imp[1])
                if m is not None:
                    cls = m.classes.get(imp[2])
            if cls is not None:
                root = cls.lock_root(attr)
                if root is not None:
                    return "%s:%s.%s" % (cls.module.modkey, cls.name, root)
        return None

    # ---------------------------------------------------------- reachability
    def thread_entries(self):
        """fn keys spawned as Thread targets / Timer callbacks anywhere."""
        out = set()
        for fn in self.functions.values():
            out.update(fn.thread_targets)
        return out

    def thread_reach(self):
        """{fn_key: frozenset(entry keys that can reach it on a spawned
        thread)} over the resolved call graph."""
        if self._reach_cache is not None:
            return self._reach_cache
        edges = {}
        for fn in self.functions.values():
            edges[fn.key] = {c for c, _n, _h in fn.calls if c is not None}
        reach = {}
        for entry in sorted(self.thread_entries()):
            stack, seen = [entry], set()
            while stack:
                cur = stack.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                reach.setdefault(cur, set()).add(entry)
                stack.extend(edges.get(cur, ()))
        self._reach_cache = {k: frozenset(v) for k, v in reach.items()}
        return self._reach_cache

    def locks_acquired_transitive(self, fn_key):
        """Every canonical lock acquired by ``fn_key`` or (resolved)
        callees, any depth — the RHS of a held-while-calling deadlock
        edge. Computed as a whole-graph fixpoint (lock sets only grow,
        so it converges), NOT per-function memoized recursion: a cycle
        guard's partial result must never be cached as final, or mutual
        recursion silently under-approximates and R009 misses real
        deadlocks."""
        if not self._translock_cache:
            sets = {}
            callees = {}
            for key, fn in self.functions.items():
                sets[key] = {lock for _held, lock, _n in fn.acquires}
                callees[key] = {c for c, _n, _h in fn.calls
                                if c is not None and c in self.functions}
            changed = True
            while changed:
                changed = False
                for key in sets:
                    merged = sets[key]
                    for c in callees[key]:
                        extra = sets[c] - merged
                        if extra:
                            merged |= extra
                            changed = True
            self._translock_cache = {k: frozenset(v)
                                     for k, v in sets.items()}
        return self._translock_cache.get(fn_key, frozenset())

    def reentrant_locks(self):
        """Canonical ids of REENTRANT locks (RLock, argless Condition):
        re-acquiring one while held is legal, so R009 must not report
        their self-edges as 1-cycle deadlocks. Order inversions between
        two locks deadlock regardless of reentrancy and stay reported."""
        out = set()
        for mod in self.modules.values():
            for name in mod.global_reentrant:
                out.add("%s::%s" % (mod.modkey, name))
        for cls in self.classes.values():
            for attr in cls.reentrant_attrs:
                if cls.lock_root(attr) == attr:
                    out.add("%s:%s.%s" % (cls.module.modkey, cls.name,
                                          attr))
        return out

    def callers(self):
        """{fn_key: set(keys of functions with a resolved call to it)} —
        the reverse call graph (Thread spawns are NOT call edges: the
        spawner runs on its own thread, the target on the new one)."""
        if self._callers_cache is None:
            out = {}
            for key, fn in self.functions.items():
                for callee, _n, _h in fn.calls:
                    if callee is not None:
                        out.setdefault(callee, set()).add(key)
            self._callers_cache = out
        return self._callers_cache

    def traced_functions(self):
        """fn keys whose bodies run under a jax trace: passed to a
        jax.jit-family wrapper directly, via a callee's jitted parameter,
        or (transitively) called from such a function."""
        traced = set()
        for mod in self.modules.values():
            traced |= mod.jit_marks_global
        for fn in self.functions.values():
            traced |= fn.jit_marks
            # interprocedural: an argument passed into a callee's
            # jit-wrapped parameter position gets traced too
            for callee, node, _h in fn.calls:
                cal = self.functions.get(callee) if callee else None
                if cal is None or not cal.jit_param_names:
                    continue
                pns = cal.params_no_self
                for i, arg in enumerate(node.args):
                    if i < len(pns) and pns[i] in cal.jit_param_names \
                            and isinstance(arg, ast.Name):
                        target = self.resolve_call_target(
                            fn.module, fn, arg, {})
                        if isinstance(target, FunctionInfo):
                            traced.add(target.key)
        # close over calls made from traced functions
        stack = list(traced)
        while stack:
            cur = stack.pop()
            fn = self.functions.get(cur)
            if fn is None:
                continue
            for callee, _n, _h in fn.calls:
                if callee is not None and callee not in traced:
                    target = self.functions.get(callee)
                    if isinstance(target, FunctionInfo):
                        traced.add(callee)
                        stack.append(callee)
        return traced


def _terminates(body):
    """Does this block end by leaving the enclosing flow (return/raise/
    break/continue)? Used for guard-style early exits."""
    return bool(body) and isinstance(body[-1], (ast.Return, ast.Raise,
                                                ast.Break, ast.Continue))


# --------------------------------------------------------------- body walk
class _FunctionWalker:
    """One pass over a function body: held-lock tracking + summary
    collection + call resolution."""

    def __init__(self, index, fn):
        self.index = index
        self.fn = fn
        self.mod = fn.module
        self.local_types = {}           # name -> ClassInfo
        self._collect_locals()

    @staticmethod
    def _binding_names(target):
        """Names a target expression BINDS: plain names and tuple/star
        unpacks only — a Subscript/Attribute store mutates an object, it
        does not create a local."""
        if isinstance(target, ast.Name):
            yield target.id
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                yield from _FunctionWalker._binding_names(elt)
        elif isinstance(target, ast.Starred):
            yield from _FunctionWalker._binding_names(target.value)

    def _collect_locals(self):
        fn = self.fn
        fn.locals_.update(fn.params)
        args = fn.node.args
        fn.locals_.update(a.arg for a in args.kwonlyargs)
        if args.vararg:
            fn.locals_.add(args.vararg.arg)
        if args.kwarg:
            fn.locals_.add(args.kwarg.arg)
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Global):
                fn.global_decls.update(node.names)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn.node:
                fn.locals_.add(node.name)
            elif isinstance(node, ast.comprehension):
                fn.locals_.update(self._binding_names(node.target))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                   ast.For, ast.AsyncFor)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    fn.locals_.update(self._binding_names(t))
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        fn.locals_.update(
                            self._binding_names(item.optional_vars))
            elif isinstance(node, ast.ExceptHandler) and node.name:
                fn.locals_.add(node.name)
        fn.locals_ -= fn.global_decls

    # ---------------------------------------------------------------- run
    def run(self):
        self.visit_block(self.fn.node.body, [])

    @staticmethod
    def _apply_transitions(transitions, held):
        """Fold '+lock'/'-lock' transitions from bare acquire()/release()
        calls into the MUTABLE held list."""
        for t in transitions:
            if t.startswith("-"):
                try:
                    held.remove(t[1:])
                except ValueError:
                    pass
            elif t not in held:
                held.append(t)

    def visit_block(self, stmts, held):
        """``held`` is a MUTABLE list shared with the enclosing linear
        control flow: bare acquire()/release() transitions must
        propagate across If/For/While/Try nesting — the canonical
        `lock.acquire(); try: ... finally: lock.release()` form spans
        three nesting levels, and the timed `if lock.acquire(timeout=):`
        form acquires inside a test. Only `with`-scoped locks are
        block-local (the with-exit releases them)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                # separate FunctionInfo / scope
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                # items acquire LEFT TO RIGHT: `with a, b:` holds a while
                # acquiring b, exactly like the nested spelling — each
                # item records the ACCUMULATED held set, not the
                # pre-statement one, or the a->b deadlock edge vanishes
                body_held = list(held)
                with_locks = []
                for item in stmt.items:
                    self._apply_transitions(
                        self.scan_expr(item.context_expr,
                                       tuple(body_held)), body_held)
                    lock = self.index.canonical_lock(
                        self.mod, self.fn, item.context_expr,
                        self.local_types)
                    if lock is not None:
                        self.fn.acquires.append(
                            (tuple(body_held), lock, item.context_expr))
                        body_held.append(lock)
                        with_locks.append(lock)
                self.visit_block(stmt.body, body_held)
                # sync bare transitions made inside the with body back to
                # the parent flow — minus the with-scoped locks, which
                # the with-exit releases
                held[:] = [l for l in held if l in body_held]
                for l in body_held:
                    if l not in held and l not in with_locks:
                        held.append(l)
            elif isinstance(stmt, ast.If):
                # the timed `if lock.acquire(timeout=):` form holds the
                # lock ONLY on the success branch: the plain spelling
                # guards the body, `if not lock.acquire(...):` guards the
                # orelse — the failure branch runs WITHOUT the lock, and
                # treating it as held fabricates deadlock edges
                trans = self.scan_expr(stmt.test, tuple(held))
                acq = [t for t in trans if not t.startswith("-")]
                self._apply_transitions(
                    [t for t in trans if t.startswith("-")], held)
                if acq:
                    succ_held = list(held)
                    self._apply_transitions(acq, succ_held)
                    negated = isinstance(stmt.test, ast.UnaryOp) \
                        and isinstance(stmt.test.op, ast.Not)
                    if negated:
                        self.visit_block(stmt.body, list(held))
                        self.visit_block(stmt.orelse, succ_held)
                        if _terminates(stmt.body):
                            # `if not lock.acquire(...): return` — the
                            # failure path exits, so everything AFTER
                            # the guard runs with the lock held
                            self._apply_transitions(acq, held)
                    else:
                        self.visit_block(stmt.body, succ_held)
                        self.visit_block(stmt.orelse, list(held))
                        if _terminates(stmt.orelse):
                            self._apply_transitions(acq, held)
                    # otherwise after the if: not held (the canonical
                    # timed form releases inside the success branch)
                else:
                    self.visit_block(stmt.body, held)
                    self.visit_block(stmt.orelse, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._apply_transitions(
                    self.scan_expr(stmt.iter, tuple(held)), held)
                self.scan_expr(stmt.target, tuple(held))
                self.visit_block(stmt.body, held)
                self.visit_block(stmt.orelse, held)
            elif isinstance(stmt, ast.While):
                self._apply_transitions(
                    self.scan_expr(stmt.test, tuple(held)), held)
                self.visit_block(stmt.body, held)
                self.visit_block(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                self.visit_block(stmt.body, held)
                for h in stmt.handlers:
                    self.visit_block(h.body, held)
                self.visit_block(stmt.orelse, held)
                self.visit_block(stmt.finalbody, held)
            else:
                self._apply_transitions(
                    self.scan_expr(stmt, tuple(held)), held)

    # ------------------------------------------------------------- scanning
    def scan_expr(self, root, held):
        """Scan one statement/expression subtree (nested function and
        lambda bodies pruned). Returns lock transitions from bare
        acquire()/release() calls ('+id' appended plain, release as
        '-id')."""
        transitions = []
        stack = [root]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(node, ast.Call):
                t = self.handle_call(node, held)
                if t:
                    transitions.append(t)
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                self.handle_assign(node, held)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                self.handle_name_load(node, held)
            elif isinstance(node, ast.Attribute):
                self.handle_attr(node, held)
            stack.extend(ast.iter_child_nodes(node))
        return transitions

    def handle_call(self, node, held):
        func = node.func
        name = terminal_name(func)
        # lock transitions for bare acquire/release in straight-line code
        if isinstance(func, ast.Attribute) and name in ("acquire",
                                                        "release"):
            lock = self.index.canonical_lock(self.mod, self.fn, func.value,
                                             self.local_types)
            if lock is not None:
                if name == "acquire":
                    self.fn.acquires.append((held, lock, node))
                    return lock
                return "-" + lock
        # host-device sync sites (shared definition with per-file R001;
        # cost_analysis/memory_analysis are per-dispatch XLA analysis
        # walks — same hot-path poison, same rule)
        if isinstance(func, ast.Attribute) and name in (
                "asnumpy", "item", "cost_analysis", "memory_analysis"):
            self.fn.syncs.append((".%s()" % name, node))
        elif isinstance(func, ast.Attribute) and name == "asarray" \
                and isinstance(func.value, ast.Name) \
                and func.value.id in ("np", "onp", "numpy"):
            self.fn.syncs.append(("%s.asarray()" % func.value.id, node))
        # thread / timer spawns
        if name in ("Thread", "Timer"):
            target = None
            for kw in node.keywords:
                if kw.arg in ("target", "function"):
                    target = kw.value
            if target is None and name == "Timer" and len(node.args) >= 2:
                target = node.args[1]
            if target is not None:
                resolved = self.index.resolve_call_target(
                    self.mod, self.fn, target, self.local_types)
                if isinstance(resolved, FunctionInfo):
                    self.fn.thread_targets.append(resolved.key)
        # jax.jit-family wrapper?
        ext = self.index.resolve_external(self.mod, func, self.fn)
        if ext.startswith("jax.") and ext.split(".")[-1] in JIT_WRAPPERS \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                if arg.id in self.fn.params:
                    self.fn.jit_param_names.add(arg.id)
                else:
                    target = self.index.resolve_call_target(
                        self.mod, self.fn, arg, self.local_types)
                    if isinstance(target, FunctionInfo):
                        self.fn.jit_marks.add(target.key)
            # jax.jit(f)(...) immediate-call form: the parent Call is a
            # boundary site (caught below when the parent is visited)
        # call-graph edge + boundary call sites
        callee = self.index.resolve_call_target(self.mod, self.fn, func,
                                                self.local_types)
        if isinstance(callee, ClassInfo):
            init = callee.resolve_method("__init__")
            self.fn.calls.append((init.key if init else None, node, held))
        elif isinstance(callee, FunctionInfo):
            self.fn.calls.append((callee.key, node, held))
        else:
            self.fn.calls.append((None, node, held))
        self._maybe_boundary_callsite(node)
        return None

    def _maybe_boundary_callsite(self, node):
        """Is THIS call a jit-boundary invocation (R011's subject)?"""
        func = node.func
        kind = None
        if isinstance(func, ast.Name) \
                and self.local_types.get(func.id) in ("jit", "step"):
            kind = self.local_types[func.id]
        elif isinstance(func, ast.Name) \
                and func.id not in self.fn.locals_ \
                and func.id in self.mod.boundary_globals:
            kind = self.mod.boundary_globals[func.id]
        elif isinstance(func, ast.Attribute) \
                and isinstance(func.value, ast.Name) \
                and func.value.id == "self" and self.fn.cls is not None \
                and func.attr in self.fn.cls.step_attrs:
            kind = "step"
        elif isinstance(func, ast.Call):
            ext = self.index.resolve_external(self.mod, func.func, self.fn)
            if ext.startswith("jax.") \
                    and ext.split(".")[-1] in JIT_WRAPPERS:
                kind = "jit"
        if kind is None and self._is_aot_boundary(func):
            kind = "jit"
        if kind:
            self.fn.jit_callsites.append((node, kind))

    def _is_aot_boundary(self, func):
        """aot.compile_cached(...)-family call? Resolved project-locally
        (the callee is a function named in AOT_BOUNDARY_FUNCS defined in
        an ``aot`` module) or through import aliases when the aot module
        is outside the analysis root (``from incubator_mxnet_tpu.aot
        import compile_cached``)."""
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name not in AOT_BOUNDARY_FUNCS:
            return False
        resolved = self.index.resolve_call_target(self.mod, self.fn, func,
                                                  self.local_types)
        if isinstance(resolved, FunctionInfo):
            return resolved.module.dotted.split(".")[-1] == AOT_MODULE_NAME
        ext = self.index.resolve_external(self.mod, func, self.fn)
        parts = ext.split(".")
        return len(parts) >= 2 and parts[-1] in AOT_BOUNDARY_FUNCS \
            and parts[-2] == AOT_MODULE_NAME

    def handle_assign(self, node, held):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        value = getattr(node, "value", None)
        aug = isinstance(node, ast.AugAssign)
        # local type binding: x = ClassName(...) / x = jax.jit(...) /
        # x = TrainStep(...)-family
        if isinstance(node, ast.Assign) and isinstance(value, ast.Call) \
                and len(targets) == 1 and isinstance(targets[0], ast.Name):
            tname = targets[0].id
            resolved = self.index.resolve_call_target(
                self.mod, self.fn, value.func, self.local_types)
            ext = self.index.resolve_external(self.mod, value.func,
                                              self.fn)
            if ext.startswith("jax.") \
                    and ext.split(".")[-1] in JIT_WRAPPERS:
                self.local_types[tname] = "jit"
            elif isinstance(resolved, ClassInfo):
                if resolved.name in STEP_CLASSES or any(
                        b.name in STEP_CLASSES for b in resolved.bases):
                    self.local_types[tname] = "step"
                else:
                    self.local_types[tname] = resolved
        for t in targets:
            self.handle_store_target(t, node, held, aug)

    def handle_store_target(self, t, node, held, aug):
        key = self.state_key(t, store=True)
        if key is not None:
            self.fn.state_writes.append((key, node, held))
            if aug:
                self.fn.state_reads.append((key, node, held))

    def handle_name_load(self, node, held):
        name = node.id
        if name in self.fn.locals_:
            return
        if name in self.mod.globals_ \
                and self.mod.global_kinds.get(name) not in ("lock", "event",
                                                            "tlocal"):
            self.fn.state_reads.append(
                (("global", self.mod.modkey, name), node, held))

    def handle_attr(self, node, held):
        if isinstance(node.ctx, ast.Load):
            key = self.state_key(node, store=False)
            if key is not None:
                self.fn.state_reads.append((key, node, held))

    def state_key(self, t, store):
        """Shared-state key for a store/load target, or None.
        ('self', class_key, attr) | ('global', modkey, name)."""
        fn, mod = self.fn, self.mod
        if isinstance(t, ast.Name):
            if not store and t.id in fn.locals_:
                return None
            if t.id in fn.global_decls or (not store
                                           and t.id in mod.globals_):
                if mod.global_kinds.get(t.id) in ("lock", "event", "tlocal"):
                    return None
                if store and t.id not in fn.global_decls:
                    return None
                return ("global", mod.modkey, t.id)
            return None
        if isinstance(t, ast.Subscript):
            base = t.value
            if isinstance(base, ast.Name):
                if base.id in fn.locals_ and base.id not in fn.global_decls:
                    return None
                if base.id in mod.globals_ \
                        and mod.global_kinds.get(base.id) not in (
                            "lock", "event", "tlocal"):
                    return ("global", mod.modkey, base.id)
                return None
            if isinstance(base, ast.Attribute):
                return self.state_key(base, store)
            return None
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name):
            base, attr = t.value.id, t.attr
            if base == "self" and fn.cls is not None:
                if attr in fn.cls.lock_attrs or attr in fn.cls.sync_attrs:
                    return None
                owner = fn.cls
                for b in fn.cls.bases:
                    if b.lock_root(attr) is not None \
                            or attr in b.sync_attrs:
                        return None
                return ("self", owner.key, attr)
            if base in mod.classes:       # ClassName.attr class state
                cls = mod.classes[base]
                if cls.lock_root(attr) is not None \
                        or attr in cls.sync_attrs:
                    return None           # sync object, not shared state
                return ("self", cls.key, attr)
            return None
        return None


# ------------------------------------------------------------------ driver
def build_index(paths, root):
    """Build the whole-program index for every FULL-profile .py file under
    ``paths`` (tools/ and tests/ run the relaxed per-file profile only and
    are excluded from whole-program analysis). Unparseable files are
    skipped here — the per-file phase already reports them as E000."""
    import os as _os
    index = ProjectIndex(root)
    for path in iter_py_files(paths):
        rel = _os.path.relpath(path, root)
        if rules_for_path(rel) is not None:
            continue                    # relaxed profile: per-file only
        try:
            ctx = get_context(path, root)
        except (SyntaxError, ValueError, OSError):
            continue
        index.add_module(ctx)
    index.finalize_imports()
    index.scan_module_boundaries()
    index.scan_class_attrs()
    for fn in index.functions.values():
        _FunctionWalker(index, fn).run()
    return index
