"""mxtpulint core: file walking, suppression comments, baseline, reports.

The engine is deliberately dependency-free (stdlib ``ast`` + ``re`` only):
the serving image that runs CI must not grow a lint dependency any more
than it grows a prometheus client (see tools/promcheck.py).

Three escape hatches, in order of preference:

1. **Fix the code** — every rule names the concrete runtime failure it
   prevents (docs/STATIC_ANALYSIS.md has a before/after per rule).
2. **Per-line suppression** — ``# mxtpulint: disable=R001`` (comma list,
   or ``disable=all``) on the offending line marks a reviewed-deliberate
   exception; pair it with a WHY comment.
3. **Baseline** — ``tools/mxtpulint/baseline.json`` grandfathers existing
   findings so the CI gate can land before a long fix queue drains.
   Entries match on (path, rule, stripped source text), not line numbers,
   so unrelated edits don't resurrect them. ``--write-baseline``
   regenerates it; the goal state is an empty list.

Report shape (shared with ``tools/promcheck.py --json`` so CI can
aggregate both gates with one parser)::

    {"tool": "<name>", "ok": bool,
     "findings": [{"path", "line", "rule", "message"}, ...],
     "counts": {"R001": 2, ...}, "baselined": <int>}
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re

__all__ = ["Finding", "FileContext", "rule", "RULES", "lint_file",
           "lint_paths", "iter_py_files", "load_baseline", "save_baseline",
           "apply_baseline", "make_report", "DEFAULT_BASELINE",
           "get_context", "rules_for_path", "filter_suppressed",
           "RELAXED_PREFIXES", "RELAXED_RULES"]

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")
# Baseline/report paths are repo-root-relative (two levels above this
# file), NOT cwd-relative: the baseline must match no matter where the
# gate is invoked from.
REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

RULES = {}          # rule id -> (title, check_fn)


def rule(rule_id, title):
    """Register ``fn(ctx) -> iterable[Finding]`` under ``rule_id``."""
    def deco(fn):
        RULES[rule_id] = (title, fn)
        return fn
    return deco


class Finding:
    """One lint hit; ``text`` (the stripped source line) is the
    line-number-independent half of the baseline key."""

    __slots__ = ("path", "line", "col", "rule", "message", "text")

    def __init__(self, path, line, col, rule_id, message, text=""):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule_id
        self.message = message
        self.text = text

    def baseline_key(self):
        return (self.path, self.rule, self.text)

    def to_json(self):
        return {"path": self.path, "line": self.line, "rule": self.rule,
                "message": self.message}

    def __repr__(self):
        return "%s:%d:%d: %s %s" % (self.path, self.line, self.col,
                                    self.rule, self.message)


# ---------------------------------------------------------------- suppression
_SUPPRESS_RE = re.compile(r"#\s*mxtpulint:\s*disable=([A-Za-z0-9_,\s]+)")


def suppressions(src_lines):
    """{1-based line -> set of rule ids (or {'all'})} from per-line
    ``# mxtpulint: disable=R00x[,R00y]`` comments."""
    out = {}
    for i, line in enumerate(src_lines, 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {tok.strip() for tok in m.group(1).split(",")
                      if tok.strip()}
    return out


# ---------------------------------------------------------------- file context
class FileContext:
    """Parsed file + the cross-rule indexes every rule shares: parent
    links, function qualnames, thread-target functions, telemetry-metric
    and lock variable names."""

    def __init__(self, path, relpath, src):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.modkey = self.relpath[:-3] if self.relpath.endswith(".py") \
            else self.relpath
        self.basename = os.path.basename(path)
        self.src_lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self._parents = {}
        self.qualnames = {}          # FunctionDef/AsyncFunctionDef -> "A.b.c"
        # binding-accurate time-module tracking (R006): names bound to the
        # time MODULE (`import time`, `import time as _time`) vs names
        # bound to the time.time FUNCTION (`from time import time [as x]`).
        # `from time import perf_counter as time` binds neither.
        self.time_module_aliases = set()
        self.walltime_func_names = set()
        # binding-accurate jax.jit tracking (R012): names bound to jax's
        # jit FUNCTION (`from jax import jit [as x]`) — a bare `jit(...)`
        # call is only jax's if the binding says so (`from numba import
        # jit` must not fire jax-donation advice).
        self.jax_jit_aliases = set()
        self._index()

    # -- indexes -----------------------------------------------------------
    def _index(self):
        stack = []
        def visit(node):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                stack.append(node.name)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.qualnames[node] = ".".join(stack)
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        self.time_module_aliases.add(alias.asname or "time")
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name == "time":
                        self.walltime_func_names.add(alias.asname
                                                     or alias.name)
            if isinstance(node, ast.ImportFrom) and node.module == "jax":
                for alias in node.names:
                    if alias.name == "jit":
                        self.jax_jit_aliases.add(alias.asname
                                                 or alias.name)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                stack.pop()
        visit(self.tree)

    # -- navigation helpers ------------------------------------------------
    def parent(self, node):
        return self._parents.get(node)

    def ancestors(self, node):
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_functions(self, node):
        """Innermost-first chain of enclosing function defs."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield anc

    def walk(self, *types):
        for node in ast.walk(self.tree):
            if not types or isinstance(node, types):
                yield node

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.src_lines):
            return self.src_lines[lineno - 1].strip()
        return ""

    def finding(self, node, rule_id, message):
        return Finding(self.relpath, node.lineno,
                       getattr(node, "col_offset", 0), rule_id, message,
                       self.line_text(node.lineno))


def terminal_name(node):
    """Rightmost identifier of a Name/Attribute chain ('' otherwise):
    ``self._worker`` -> ``_worker``, ``t`` -> ``t``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


# ------------------------------------------------------------- context cache
# The whole-program index phase (tools/mxtpulint/project.py) and the
# per-file rule phase both need every file's AST: without a cache each
# lint run would parse the tree twice (and repeated programmatic calls,
# e.g. the test suite's gate assertions, many times more). Contexts are
# cached per (path, root) and validated by CONTENT HASH, not mtime — an
# edit-and-revert or a copied checkout never serves a stale tree.
_CTX_CACHE = {}
_CTX_CACHE_MAX = 4096


def get_context(path, root):
    """Parsed ``FileContext`` for ``path`` (repo-relative to ``root``),
    served from the content-hash cache. Raises like open()/ast.parse on
    unreadable/unparseable sources — callers turn that into E000."""
    with open(path, "rb") as f:
        raw = f.read()
    digest = hashlib.sha1(raw).hexdigest()
    key = (os.path.abspath(path), os.path.abspath(root))
    hit = _CTX_CACHE.get(key)
    if hit is not None and hit[0] == digest:
        return hit[1]
    src = raw.decode("utf-8")
    ctx = FileContext(path, os.path.relpath(path, root), src)
    if len(_CTX_CACHE) >= _CTX_CACHE_MAX:
        _CTX_CACHE.clear()       # wholesale: simple and bounded
    _CTX_CACHE[key] = (digest, ctx)
    return ctx


# ------------------------------------------------------------- path profiles
# The gate covers the runtime package under the FULL rule set, while
# tools/ and tests/ run a relaxed profile (lock/thread/clock hygiene
# only): test helpers and the linter itself spawn threads and take locks
# too, but hot-path/telemetry/jit rules are framework-runtime concepts.
# The whole-program passes (R009+) likewise only analyze full-profile
# files.
RELAXED_PREFIXES = ("tools/", "tests/")
RELAXED_RULES = frozenset({"R003", "R005", "R006"})


def rules_for_path(relpath):
    """Rule-id set for one repo-relative path, or None meaning ALL rules
    (full profile)."""
    rel = relpath.replace(os.sep, "/")
    for prefix in RELAXED_PREFIXES:
        if rel == prefix.rstrip("/") or rel.startswith(prefix):
            return RELAXED_RULES
    return None


# ---------------------------------------------------------------- the runner
SKIP_DIRS = {"__pycache__", ".git", "build", "dist", "node_modules"}


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS
                                 and not d.startswith(".")
                                 and not d.endswith(".egg-info"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_file(path, root=None, only_rules=None, keep_suppressed=False):
    """Lint one file; returns non-suppressed findings (suppressed ones are
    dropped here, before baseline matching). ``keep_suppressed=True``
    skips that drop — the suppression AUDIT needs the raw finding set to
    decide which disable comments still suppress anything."""
    root = root or REPO_ROOT
    relpath = os.path.relpath(path, root)
    try:
        ctx = get_context(path, root)
    except SyntaxError as e:
        return [Finding(relpath.replace(os.sep, "/"), e.lineno or 0, 0,
                        "E000", "syntax error: %s" % e.msg)]
    except (ValueError, OSError) as e:
        # one unreadable file must fail AS A FINDING, not take the whole
        # gate down with a traceback. ValueError covers both non-UTF-8
        # bytes (UnicodeDecodeError) and ast.parse's bare ValueError for
        # null bytes on py3.10/3.11.
        return [Finding(relpath.replace(os.sep, "/"), 0, 0, "E000",
                        "unreadable source (%s)" % e)]
    findings = []
    for rule_id, (_title, fn) in sorted(RULES.items()):
        if only_rules and rule_id not in only_rules:
            continue
        findings.extend(fn(ctx))
    if not keep_suppressed:
        findings = filter_suppressed(findings, {ctx.relpath: ctx})
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_paths(paths, root=None, only_rules=None, profiled=False,
               keep_suppressed=False):
    """Per-file rule phase over ``paths``. With ``profiled=True`` each
    file runs only its path profile's rules (tools/ and tests/ get the
    relaxed lock/thread/clock subset — see ``rules_for_path``)."""
    root = root or REPO_ROOT
    findings = []
    for path in iter_py_files(paths):
        only = only_rules
        if profiled:
            profile = rules_for_path(os.path.relpath(path, root))
            if profile is not None:
                only = profile if only_rules is None \
                    else (profile & set(only_rules))
                if not only:
                    # none of the requested rules apply under this
                    # path's profile — an empty set must SKIP the file
                    # (a falsy only_rules would mean "no filter" and
                    # run everything the user excluded)
                    continue
        findings.extend(lint_file(path, root=root, only_rules=only,
                                  keep_suppressed=keep_suppressed))
    return findings


def filter_suppressed(findings, ctx_by_relpath):
    """Drop findings whose line carries a matching per-line suppression —
    the same check ``lint_file`` applies, exposed for the whole-program
    passes (their findings are produced outside any one file's run)."""
    sup_by_path = {rel: suppressions(ctx.src_lines)
                   for rel, ctx in ctx_by_relpath.items()}
    kept = []
    for f in findings:
        rules_off = sup_by_path.get(f.path, {}).get(f.line, ())
        if "all" in rules_off or f.rule in rules_off:
            continue
        kept.append(f)
    return kept


def _comment_suppression_lines(src_lines):
    """Lines whose suppression marker sits in a REAL comment token.
    ``suppressions()`` is regex-over-raw-lines (cheap, and a docstring
    line never has findings to wrongly swallow), but the AUDIT must not
    flag doc examples of the disable syntax — or lint-test fixtures
    embedding it in strings — as dead suppressions. None on a tokenize
    failure: the caller audits every candidate line rather than none."""
    import io
    import tokenize
    lines = set()
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO("\n".join(src_lines) + "\n").readline):
            if tok.type == tokenize.COMMENT \
                    and _SUPPRESS_RE.search(tok.string):
                lines.add(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError,
            ValueError):
        return None
    return lines


def audit_suppressions(files, raw_findings, root=None,
                       live_findings=None, baseline_counts=None):
    """The suppression/baseline hygiene audit (``--check-suppressions``):

    - **X001** — a ``# mxtpulint: disable=R00x`` comment naming a rule
      that no longer fires at that line (the code was fixed, the rule
      retired, or the id was typo'd), or ``disable=all`` on a line where
      nothing fires. A dead suppression is a live hazard: it silently
      masks the NEXT real finding that lands on the line.
    - **X002** — a baseline entry whose ``(path, rule, text)`` key
      exceeds the live finding count for that key: grandfathered debt
      that was actually paid but never collected from the file.

    ``raw_findings`` must be a pre-suppression run (``keep_suppressed``)
    over the same ``files``; ``live_findings`` the normal filtered run
    (what the baseline matches against). Both audits are advisory until
    wired as findings — ci/run.sh runs them default-on in the lint
    stage, and they are never baselineable themselves."""
    root = root or REPO_ROOT
    raw_at = {}
    for f in raw_findings:
        raw_at.setdefault(f.path, {}).setdefault(f.line, set()).add(f.rule)
    known = set(RULES)
    try:
        from .interproc import PROJECT_RULES
        known |= set(PROJECT_RULES)
    except Exception:
        pass
    audit = []
    for path in files:
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            ctx = get_context(path, root)
        except (SyntaxError, ValueError, OSError):
            continue        # lint_file already reported E000 for it
        real = _comment_suppression_lines(ctx.src_lines)
        for line, rules_off in sorted(suppressions(ctx.src_lines).items()):
            if real is not None and line not in real:
                continue    # disable syntax inside a string literal:
                            # documentation/fixture, not a suppression
            fired = raw_at.get(relpath, {}).get(line, set())
            if "all" in rules_off:
                if not fired:
                    audit.append(Finding(
                        relpath, line, 0, "X001",
                        "dead suppression: 'disable=all' on a line where "
                        "no rule fires — delete the comment (left in "
                        "place it silently masks the next real finding "
                        "here)", ctx.line_text(line)))
                continue
            dead = sorted(r for r in rules_off if r not in fired)
            if dead:
                audit.append(Finding(
                    relpath, line, 0, "X001",
                    "dead suppression: %s no longer fire(s) at this line "
                    "— drop %s from the disable comment%s"
                    % (", ".join(dead), ", ".join(dead),
                       "" if all(r in known for r in dead)
                       else " (unknown rule id — typo?)"),
                    ctx.line_text(line)))
    if baseline_counts:
        live = {}
        for f in live_findings or ():
            k = f.baseline_key()
            live[k] = live.get(k, 0) + 1
        for key in sorted(baseline_counts):
            path, rule_id, text = key
            excess = baseline_counts[key] - live.get(key, 0)
            if excess > 0:
                audit.append(Finding(
                    path, 0, 0, "X002",
                    "stale baseline entry: %d grandfathered %s finding(s) "
                    "matching %r no longer occur — the debt was paid; "
                    "shrink the baseline (--update-baseline)"
                    % (excess, rule_id, (text or "<no text>")[:60])))
    audit.sort(key=lambda f: (f.path, f.line, f.rule))
    return audit


# ---------------------------------------------------------------- baseline
def load_baseline(path):
    """Baseline file -> multiset {key: count}. A missing file is an empty
    baseline (the gate still works before the file exists)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    counts = {}
    for entry in data.get("findings", []):
        key = (entry["path"], entry["rule"], entry.get("text", ""))
        counts[key] = counts.get(key, 0) + 1
    return counts


def save_baseline(path, findings):
    data = {"version": 1,
            "comment": "grandfathered mxtpulint findings — shrink to zero; "
                       "matched on (path, rule, text), line-number free",
            "findings": [{"path": f.path, "rule": f.rule, "text": f.text}
                         for f in findings]}
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def apply_baseline(findings, baseline_counts):
    """Split findings into (new, grandfathered) against the multiset."""
    remaining = dict(baseline_counts)
    new, old = [], []
    for f in findings:
        key = f.baseline_key()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# ---------------------------------------------------------------- reporting
def make_report(tool, findings, baselined=0):
    """The shared CI-aggregatable JSON shape (see module docstring)."""
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {"tool": tool, "ok": not findings,
            "findings": [f.to_json() for f in findings],
            "counts": counts, "baselined": baselined}
