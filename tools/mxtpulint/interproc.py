"""Phase 2 of the whole-program analyzer: interprocedural passes.

These run over the project index (project.py) and see the framework as
ONE program: every thread entry point, every lock, every path into a jit
boundary. Four passes:

- **R009 lock-order cycles** — build the held-while-acquiring graph
  (lock A held when lock B is acquired, directly or anywhere down the
  resolved call graph) and report strongly-connected components: two
  threads taking the locks in opposite orders deadlock with both stacks
  parked inside ``acquire``. Re-acquiring a held non-reentrant lock is
  the 1-cycle of the same defect.
- **R010 cross-thread shared state** — an attribute/global written in a
  function reachable from a ``Thread``/``Timer`` entry and read in some
  other function with NO common lock across the two sites. Plain stores
  are GIL-atomic, but the reader still observes torn multi-field state
  and stale values with no happens-before edge; every real hit is either
  locked, redesigned, or carries a reviewed suppression explaining why
  the unlocked read is sound.
- **R011 jit retrace hazards** — Python values flowing into a
  ``jax.jit``/``TrainStep``/``EvalStep`` call site that force a silent
  recompile: dict/set literals (fresh unhashable objects per call) and
  per-call-varying scalars (``time.*``, ``random.*``, ``next()`` ...).
  Plus data-dependent ``if``/``while`` on a traced function's own
  arguments (shape/``is None``/``isinstance`` checks are trace-stable
  and exempt). Every hit is one more XLA compile the serving p99 pays.
- **call-graph-aware R001** — host-device syncs one call level deep in
  helpers invoked from the hot paths rules.py only checks inline.

Findings carry the same shape, suppression mechanism, and baseline
semantics as the per-file rules.
"""
from __future__ import annotations

import ast
import fnmatch

from .core import (REPO_ROOT, filter_suppressed, lint_paths, terminal_name)
from .project import build_index
from .rules import HOT_PATH_PATTERNS

__all__ = ["PROJECT_RULES", "project_rule", "run_project_rules", "analyze"]

PROJECT_RULES = {}          # rule id -> (title, pass_fn(index))


def project_rule(rule_id, title):
    def deco(fn):
        PROJECT_RULES[rule_id] = (title, fn)
        return fn
    return deco


def _finding(fn, node, rule_id, message):
    return fn.module.ctx.finding(node, rule_id, message)


# --------------------------------------------------------------------- R009
def _lock_edges(index):
    """(held, acquired) -> witness (fn, node, via_callee_or_None); the
    held-while-acquiring graph over every function, with lock sets
    acquired by callees folded in transitively. Self-edges on REENTRANT
    locks (RLock, argless Condition) are legal re-acquisition, not
    deadlock 1-cycles, and are dropped here; inversions BETWEEN two
    locks deadlock regardless of reentrancy and stay."""
    reentrant = index.reentrant_locks()
    edges = {}
    for key in sorted(index.functions):
        fn = index.functions[key]
        for held, lock, node in fn.acquires:
            for h in held:
                if h == lock and lock in reentrant:
                    continue
                edges.setdefault((h, lock), (fn, node, None))
        for callee, node, held in fn.calls:
            if callee is None or not held:
                continue
            for lock in index.locks_acquired_transitive(callee):
                for h in held:
                    if h == lock and lock in reentrant:
                        continue
                    edges.setdefault((h, lock), (fn, node, callee))
    return edges


def _sccs(nodes, adj):
    """Tarjan strongly-connected components (iterative)."""
    idx, low, on, order, stack, out = {}, {}, set(), [0], [], []
    for start in sorted(nodes):
        if start in idx:
            continue
        work = [(start, iter(sorted(adj.get(start, ()))))]
        idx[start] = low[start] = order[0]
        order[0] += 1
        stack.append(start)
        on.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in idx:
                    idx[nxt] = low[nxt] = order[0]
                    order[0] += 1
                    stack.append(nxt)
                    on.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on:
                    low[node] = min(low[node], idx[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == idx[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(sorted(comp))
    return out


@project_rule("R009", "lock-order cycle across threads (potential deadlock)")
def r009_lock_cycles(index):
    edges = _lock_edges(index)
    adj = {}
    for (a, b), _w in edges.items():
        adj.setdefault(a, set()).add(b)
    nodes = set(adj)
    for targets in adj.values():
        nodes |= targets
    for comp in _sccs(nodes, adj):
        cyclic = len(comp) > 1 or (comp and comp[0] in adj.get(comp[0], ()))
        if not cyclic:
            continue
        comp_set = set(comp)
        witnesses = sorted(
            ((a, b), w) for (a, b), w in edges.items()
            if a in comp_set and b in comp_set)
        parts = []
        for (a, b), (fn, node, via) in witnesses:
            hop = "%s -> %s in %s (line %d%s)" % (
                a, b, fn.key, node.lineno,
                ", via call into %s" % via if via else "")
            parts.append(hop)
        anchor_fn, anchor_node, _ = witnesses[0][1]
        yield _finding(
            anchor_fn, anchor_node, "R009",
            "lock-order cycle over {%s}: two threads taking these locks "
            "in opposite orders deadlock with both stacks inside "
            "acquire(). Edges: %s. Impose one global order (or collapse "
            "to one lock), or document why the orders can never run "
            "concurrently" % (", ".join(comp), "; ".join(parts)))


# --------------------------------------------------------------------- R010
def _single_thread_only(index, fn, entries, _seen=None):
    """True iff ``fn`` can ONLY execute on the single spawned thread of
    ``entries``: it is that entry itself (spawned, never called), or
    every resolved call site of it sits in a function with the same
    property. A call site anywhere else — a main-thread poll of a
    worker-side helper, or the entry function itself ALSO invoked
    synchronously (``Thread(target=f).start(); f()``) — means the
    function's reads race the worker's writes after all."""
    _seen = _seen or set()
    if fn.key in _seen:
        return True        # recursion inside the same cluster
    _seen.add(fn.key)
    callers = index.callers().get(fn.key)
    if fn.key in entries:
        # spawn edges are not call edges; any RESOLVED call site means
        # the entry also runs synchronously on the caller's thread
        if not callers:
            return True
    elif not callers:
        return False       # unknown invocation context: assume any thread
    reach = index.thread_reach()
    for caller_key in callers:
        if reach.get(caller_key, frozenset()) != entries:
            return False
        caller = index.functions.get(caller_key)
        if caller is None or not _single_thread_only(index, caller,
                                                     entries, _seen):
            return False
    return True


@project_rule("R010", "cross-thread shared state without a common lock")
def r010_cross_thread_state(index):
    reach = index.thread_reach()
    state = {}
    for key in sorted(index.functions):
        fn = index.functions[key]
        for skey, node, held in fn.state_writes:
            state.setdefault(skey, ([], []))[0].append((fn, node, held))
        for skey, node, held in fn.state_reads:
            state.setdefault(skey, ([], []))[1].append((fn, node, held))
    for skey in sorted(state, key=repr):
        writes, reads = state[skey]
        for fn_w, node_w, held_w in writes:
            if fn_w.is_init:
                continue    # happens-before the thread start that shares it
            entries_w = reach.get(fn_w.key)
            if not entries_w:
                continue    # only thread-side writers are the hazard here
            conflict = None
            for fn_r, node_r, held_r in reads:
                if fn_r.key == fn_w.key:
                    continue    # same function: same thread at this site
                entries_r = reach.get(fn_r.key, frozenset())
                if entries_r == entries_w and len(entries_w) == 1 \
                        and _single_thread_only(index, fn_r, entries_w) \
                        and _single_thread_only(index, fn_w, entries_w):
                    continue    # both only ever run on that one thread
                if set(held_w) & set(held_r):
                    continue    # common lock: properly synchronized pair
                # double-checked locking: an unlocked fast-path read is
                # sound when the SAME function re-reads the state under
                # the writer's lock before acting on a miss
                if held_w and any(
                        r2.key == fn_r.key and set(h2) & set(held_w)
                        for r2, _n2, h2 in reads):
                    continue
                conflict = (fn_r, node_r, held_r)
                break
            if conflict is None:
                continue
            fn_r, node_r, held_r = conflict
            kind, owner, name = skey
            what = ("attribute %r of %s" % (name, owner)) \
                if kind == "self" else ("module global %s::%s"
                                        % (owner, name))
            w_lock = ("under %s" % ", ".join(sorted(held_w))) \
                if held_w else "with no lock"
            r_lock = ("under a different lock (%s)"
                      % ", ".join(sorted(held_r))) \
                if held_r else "with no lock"
            yield _finding(
                fn_w, node_w, "R010",
                "%s is written here on thread entry %s %s, and read in "
                "%s (line %d) %s — no COMMON lock orders the two sites, "
                "so the reader can observe stale or torn state with no "
                "happens-before edge; guard both sides with one lock "
                "(or document the GIL-atomicity argument in a reviewed "
                "suppression)"
                % (what, "/".join(sorted(entries_w)), w_lock, fn_r.key,
                   node_r.lineno, r_lock))


# --------------------------------------------------------------------- R011
_VARYING_BUILTINS = {"next", "id"}
_VARYING_PREFIXES = ("time.", "random.", "datetime.", "uuid.")
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_EXEMPT_TEST_CALLS = {"isinstance", "callable", "hasattr", "getattr",
                      "len", "type"}


def _varying_call(index, mod, node, fn=None):
    """Is this Call a per-call-varying scalar source (wall clock, RNG,
    counters)? Resolved through import aliases — module-level AND
    function-scoped deferred ones (``def f(): import time`` counts), so
    ``import time as t`` and ``from time import time as now`` both
    count."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name) and f.id in _VARYING_BUILTINS:
        return "%s()" % f.id
    ext = index.resolve_external(mod, f, fn)
    for prefix in _VARYING_PREFIXES:
        if ext.startswith(prefix):
            return "%s()" % ext
    if ext == "os.urandom":
        return "os.urandom()"
    return None


def _hazard_for_arg(index, mod, arg, varying_locals, fn=None):
    if isinstance(arg, (ast.Dict, ast.DictComp)):
        return "a dict literal (a fresh unhashable Python object per call)"
    if isinstance(arg, (ast.Set, ast.SetComp)):
        return "a set literal (a fresh unhashable Python object per call)"
    v = _varying_call(index, mod, arg, fn)
    if v:
        return "a per-call-varying %s value" % v
    if isinstance(arg, ast.Name) and arg.id in varying_locals:
        return "a per-call-varying value (%s, bound from %s)" \
            % (arg.id, varying_locals[arg.id])
    if isinstance(arg, (ast.List, ast.Tuple)):
        for elt in arg.elts:
            v = _varying_call(index, mod, elt, fn)
            if v:
                return "a container holding a per-call-varying %s value" % v
    return None


def _branch_offender(test, params):
    """Param name a traced-function branch test depends on, or None.
    Identity (`is`/`is not`), isinstance/len/shape-style structure checks
    are trace-stable and exempt."""
    if isinstance(test, ast.Compare) \
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
        return None
    if isinstance(test, ast.Call) \
            and terminal_name(test.func) in _EXEMPT_TEST_CALLS:
        return None
    if isinstance(test, ast.Attribute) and test.attr in _STATIC_ATTRS:
        return None
    if isinstance(test, ast.Name):
        return test.id if test.id in params else None
    for child in ast.iter_child_nodes(test):
        hit = _branch_offender(child, params)
        if hit:
            return hit
    return None


def _iter_own_nodes(fn_node):
    """Walk a function body, pruning nested function/class bodies (they
    are separate FunctionInfos)."""
    stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@project_rule("R011", "Python value reaching a jit boundary forces retrace")
def r011_retrace_hazards(index):
    # (a) hazardous arguments at jit-boundary call sites
    for key in sorted(index.functions):
        fn = index.functions[key]
        if not fn.jit_callsites:
            continue
        # source-order scan with rebinding: `seed = time.time()` taints
        # the name, but the sanctioned `seed = jnp.asarray(seed)` wrap
        # RE-binds it to an array and must clear the taint
        assigns = sorted(
            (n for n in _iter_own_nodes(fn.node)
             if isinstance(n, ast.Assign) and len(n.targets) == 1
             and isinstance(n.targets[0], ast.Name)),
            key=lambda n: (n.lineno, n.col_offset))
        def varying_at(line):
            state = {}
            for node in assigns:
                if node.lineno >= line:
                    break
                v = _varying_call(index, fn.module, node.value, fn)
                if v is None and isinstance(
                        node.value, (ast.Dict, ast.DictComp, ast.Set,
                                     ast.SetComp)):
                    # the hoisted spelling of the inline-literal hazard:
                    # `cfg = {...}; jitted(x, cfg)` is the same fresh
                    # unhashable object per call
                    v = "dict/set literal built per call"
                if v:
                    state[node.targets[0].id] = v
                else:
                    state.pop(node.targets[0].id, None)
            return state

        for call_node, kind in fn.jit_callsites:
            varying_locals = varying_at(call_node.lineno)
            args = list(call_node.args) + [kw.value
                                           for kw in call_node.keywords]
            for arg in args:
                why = _hazard_for_arg(index, fn.module, arg,
                                      varying_locals, fn)
                if why:
                    boundary = "jax.jit'd callable" if kind == "jit" \
                        else "compiled TrainStep/EvalStep"
                    yield _finding(
                        fn, arg, "R011",
                        "argument to a %s is %s — Python-side structure/"
                        "values at a compiled boundary feed the trace "
                        "cache key or fail tracing outright: a varying "
                        "pytree structure re-traces per shape, an "
                        "unhashable value breaks any static-arg "
                        "position, non-numeric leaves raise at trace "
                        "time, and the AOT/export pipeline bakes each "
                        "distinct value into its own compiled artifact "
                        "(the compile serving p99 pays); pass arrays "
                        "(jnp.asarray) or one fixed per-process "
                        "constant" % (boundary, why))
    # (b) data-dependent Python branching inside traced functions
    traced = index.traced_functions()
    for key in sorted(traced):
        fn = index.functions.get(key)
        if fn is None:
            continue
        params = set(fn.params_no_self)
        if not params:
            continue
        for node in _iter_own_nodes(fn.node):
            if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                continue
            offender = _branch_offender(node.test, params)
            if offender:
                yield _finding(
                    fn, node, "R011",
                    "%s runs under a jax trace (reached from a jit "
                    "boundary) but branches on its argument %r in Python "
                    "— each concrete value traces a new program variant "
                    "(or raises TracerBoolConversionError); use lax.cond/"
                    "jnp.where, or hoist the decision out of the traced "
                    "function" % (fn.key, offender))


# ----------------------------------------------------- call-graph-aware R001
def _is_hot(key):
    return any(fnmatch.fnmatch(key, pat) for pat in HOT_PATH_PATTERNS)


@project_rule("R001", "host-device sync in a helper called from a hot path")
def r001_interprocedural(index):
    seen = set()
    for key in sorted(index.functions):
        fn = index.functions[key]
        if not _is_hot(fn.key):
            continue
        for callee_key, node, _held in fn.calls:
            callee = index.functions.get(callee_key) \
                if callee_key else None
            if callee is None or _is_hot(callee.key):
                continue        # inline hits are the per-file rule's job
            for what, snode in callee.syncs:
                mark = (callee.key, snode.lineno, snode.col_offset)
                if mark in seen:
                    continue
                seen.add(mark)
                if "analysis" in what:
                    # the device-truth sub-rule: cost_analysis /
                    # memory_analysis are per-dispatch XLA analysis
                    # walks, not device transfers — the remediation is
                    # the cached aot entry stats, not lazier values
                    yield _finding(
                        callee, snode, "R001",
                        "%s inside %r, which hot path %r calls "
                        "(line %d) — a per-dispatch XLA analysis walk "
                        "hiding one call level down; harvest device "
                        "truth ONCE at AOT build/load (aot.CACHE entry "
                        "stats via devstats.program_stats) and read the "
                        "cached dict in the helper"
                        % (what, callee.key, fn.key, node.lineno))
                    continue
                yield _finding(
                    callee, snode, "R001",
                    "%s inside %r, which hot path %r calls (line %d) — "
                    "the sync hides one call level down but still blocks "
                    "the dispatching thread on a device transfer; keep "
                    "the helper lazy or move the materialization off the "
                    "hot path" % (what, callee.key, fn.key, node.lineno))


# ------------------------------------------------------------- orchestration
def run_project_rules(index, only_rules=None):
    findings = []
    for rule_id in sorted(PROJECT_RULES):
        if only_rules and rule_id not in only_rules:
            continue
        _title, pass_fn = PROJECT_RULES[rule_id]
        findings.extend(pass_fn(index))
    return findings


def analyze(paths, root=None, only_rules=None, profiled=True,
            keep_suppressed=False):
    """The full two-phase run: per-file rules (path-profiled), then the
    whole-program index + interprocedural passes over the full-profile
    files, with per-line suppressions applied to both. Returns the
    combined, sorted finding list (pre-baseline).
    ``keep_suppressed=True`` leaves suppressed findings IN (both phases)
    — the raw view ``core.audit_suppressions`` diffs disable comments
    against."""
    from .core import iter_py_files
    root = root or REPO_ROOT
    # materialize the tree walk ONCE; both phases accept file lists
    files = list(iter_py_files(paths))
    findings = lint_paths(files, root=root, only_rules=only_rules,
                          profiled=profiled,
                          keep_suppressed=keep_suppressed)
    if only_rules is None or (set(only_rules) & set(PROJECT_RULES)):
        index = build_index(files, root)
        proj = run_project_rules(index, only_rules=only_rules)
        if not keep_suppressed:
            ctxs = {m.relpath: m.ctx for m in index.modules.values()}
            proj = filter_suppressed(proj, ctxs)
        findings.extend(proj)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
